#!/usr/bin/env python
"""perfdiff: typed regression verdicts over persisted performance evidence.

Compares two snapshots of the repo's on-disk performance memory —
``COST_MODEL.json`` (per-stage leg aggregates from the cost-observatory
tracer) and the ``mfu_ladder`` bank inside ``BENCH_TPU_CACHE.json`` —
and emits one typed verdict per comparable series:

- ``flat``       — the delta sits inside the noise band;
- ``improved``   — current is better by more than the band
  (lower µs for stage legs, higher MFU for ladder cells);
- ``regressed``  — current is worse by more than the band; the verdict
  carries WHICH leg regressed (``dispatch`` / ``device_exec`` /
  ``queue_wait`` / ``wire`` / ``mfu``), because "the pipeline got
  slower" is not actionable and "the wire leg got slower" is.

The noise band is derived from the evidence itself: stage legs persist
Welford aggregates (count/mean/m2), so the band is
``max(sigmas × sample-std, min_rel × baseline, min_abs)`` — a leg that
historically swings 40% does not page anyone over a 10% delta.  Ladder
cells bank single best-of measurements (no variance), so they use the
relative band alone.

A self-compare (baseline == current) is ``flat`` by construction — the
CI smoke pins that.  The report is NON-FATAL by default (exit 0, it is
an observability artifact, not a gate); ``--strict`` exits 1 when any
verdict regressed.  Every regression also increments
``nnstpu_perf_regression_total{leg}`` so a scrape of a long-lived
process that runs perfdiff periodically shows regression pressure over
time.

Usage::

    python tools/perfdiff.py                       # self-compare (flat)
    python tools/perfdiff.py --baseline old.json --current new.json
    python tools/perfdiff.py --bank-baseline old_cache.json \\
                             --bank-current BENCH_TPU_CACHE.json
    python -m tools.perfdiff --json --strict
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from nnstreamer_tpu.obs import costmodel  # noqa: E402
from nnstreamer_tpu.obs.metrics import REGISTRY  # noqa: E402

DEFAULT_SIGMAS = costmodel.BAND_SIGMAS
DEFAULT_MIN_REL = costmodel.BAND_MIN_REL
DEFAULT_MIN_ABS_US = costmodel.BAND_MIN_ABS_US


def _regression_counter(registry=None):
    registry = registry if registry is not None else REGISTRY
    return registry.counter(
        "nnstpu_perf_regression_total",
        "Regressed perfdiff verdicts, by leg "
        "(dispatch/device_exec/queue_wait/wire/mfu)", ("leg",))


def stage_band_us(leg_stat: dict, sigmas: float = DEFAULT_SIGMAS,
                  min_rel: float = DEFAULT_MIN_REL,
                  min_abs_us: float = DEFAULT_MIN_ABS_US) -> float:
    """Noise band (µs) for one persisted stage-leg aggregate — the one
    implementation lives in :func:`costmodel.leg_band_us` (forensics
    scores outliers with the same band)."""
    return costmodel.leg_band_us(leg_stat, sigmas=sigmas, min_rel=min_rel,
                                 min_abs_us=min_abs_us)


def diff_cost_models(baseline: dict, current: dict,
                     sigmas: float = DEFAULT_SIGMAS,
                     min_rel: float = DEFAULT_MIN_REL,
                     min_abs_us: float = DEFAULT_MIN_ABS_US) -> List[dict]:
    """One verdict per (stage, leg) present in BOTH documents."""
    verdicts: List[dict] = []
    b_stages = baseline.get("stages") or {}
    c_stages = current.get("stages") or {}
    for key in sorted(set(b_stages) & set(c_stages)):
        b_legs = b_stages[key].get("legs") or {}
        c_legs = c_stages[key].get("legs") or {}
        for leg in sorted(set(b_legs) & set(c_legs)):
            b = float(b_legs[leg].get("mean_us") or 0.0)
            c = float(c_legs[leg].get("mean_us") or 0.0)
            band = stage_band_us(b_legs[leg], sigmas=sigmas,
                                 min_rel=min_rel, min_abs_us=min_abs_us)
            delta = c - b
            if abs(delta) <= band:
                verdict = "flat"
            elif delta < 0:
                verdict = "improved"
            else:
                verdict = "regressed"
            verdicts.append({
                "kind": "stage", "key": key, "leg": leg,
                "baseline_us": round(b, 3), "current_us": round(c, 3),
                "delta_us": round(delta, 3), "band_us": round(band, 3),
                "verdict": verdict,
            })
    return verdicts


def diff_ladder_banks(baseline: dict, current: dict,
                      min_rel: float = DEFAULT_MIN_REL) -> List[dict]:
    """One verdict per ladder cell key present in BOTH banks (compared
    on MFU; higher is better)."""
    verdicts: List[dict] = []
    for key in sorted(set(baseline) & set(current)):
        b = (baseline[key] or {}).get("mfu")
        c = (current[key] or {}).get("mfu")
        if b is None or c is None:
            continue
        band = min_rel * abs(float(b))
        delta = float(c) - float(b)
        if abs(delta) <= band:
            verdict = "flat"
        elif delta > 0:
            verdict = "improved"
        else:
            verdict = "regressed"
        verdicts.append({
            "kind": "ladder", "key": key, "leg": "mfu",
            "baseline_mfu": round(float(b), 5),
            "current_mfu": round(float(c), 5),
            "delta_mfu": round(delta, 5), "band_mfu": round(band, 5),
            "verdict": verdict,
        })
    return verdicts


def overall_verdict(verdicts: List[dict]) -> str:
    kinds = {v["verdict"] for v in verdicts}
    if "regressed" in kinds:
        return "regressed"
    if "improved" in kinds:
        return "improved"
    return "flat"


def report(verdicts: List[dict], registry=None) -> dict:
    """Counts + overall verdict; bumps the regression counter per
    regressed leg."""
    counter = _regression_counter(registry)
    regressed_legs: Dict[str, int] = {}
    for v in verdicts:
        if v["verdict"] == "regressed":
            counter.inc(leg=v["leg"])
            regressed_legs[v["leg"]] = regressed_legs.get(v["leg"], 0) + 1
    return {
        "verdict": overall_verdict(verdicts),
        "compared": len(verdicts),
        "flat": sum(1 for v in verdicts if v["verdict"] == "flat"),
        "improved": sum(1 for v in verdicts if v["verdict"] == "improved"),
        "regressed": sum(1 for v in verdicts if v["verdict"] == "regressed"),
        "regressed_legs": regressed_legs,
        "verdicts": verdicts,
    }


def _load_bank(path: Optional[str]) -> Optional[dict]:
    if not path:
        return None
    try:
        with open(path) as f:
            doc = json.load(f)
    except Exception:  # noqa: BLE001 — absent evidence, empty comparison
        return {}
    if isinstance(doc, dict) and isinstance(doc.get("mfu_ladder"), dict):
        return doc["mfu_ladder"]
    return doc if isinstance(doc, dict) else {}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="typed perf-regression verdicts over COST_MODEL.json "
                    "+ the banked mfu ladder")
    ap.add_argument("--baseline", default=None,
                    help="baseline COST_MODEL.json (default: the "
                         "configured live path — self-compare)")
    ap.add_argument("--current", default=None,
                    help="current COST_MODEL.json (default: the "
                         "configured live path)")
    ap.add_argument("--bank-baseline", default=None,
                    help="baseline BENCH_TPU_CACHE.json (or a bare "
                         "mfu_ladder bank); ladder cells are only "
                         "compared when both bank paths are given")
    ap.add_argument("--bank-current", default=None,
                    help="current BENCH_TPU_CACHE.json")
    ap.add_argument("--sigmas", type=float, default=DEFAULT_SIGMAS)
    ap.add_argument("--min-rel", type=float, default=DEFAULT_MIN_REL)
    ap.add_argument("--min-abs-us", type=float, default=DEFAULT_MIN_ABS_US)
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 when any verdict regressed (default: "
                         "always exit 0 — the report is non-fatal)")
    args = ap.parse_args(argv)

    live = costmodel.cost_model_path()
    base_doc = costmodel.load_cost_model(args.baseline or live)
    cur_doc = costmodel.load_cost_model(args.current or live)
    verdicts = diff_cost_models(base_doc, cur_doc, sigmas=args.sigmas,
                                min_rel=args.min_rel,
                                min_abs_us=args.min_abs_us)
    b_bank = _load_bank(args.bank_baseline)
    c_bank = _load_bank(args.bank_current)
    if b_bank is not None and c_bank is not None:
        verdicts += diff_ladder_banks(b_bank, c_bank, min_rel=args.min_rel)

    rep = report(verdicts)
    if args.json:
        print(json.dumps(rep, indent=1, sort_keys=True))
    else:
        for v in verdicts:
            if v["kind"] == "stage":
                print(f"{v['verdict']:>9}  {v['key']} [{v['leg']}]  "
                      f"{v['baseline_us']} -> {v['current_us']} us  "
                      f"(band {v['band_us']})")
            else:
                print(f"{v['verdict']:>9}  {v['key']} [mfu]  "
                      f"{v['baseline_mfu']} -> {v['current_mfu']}  "
                      f"(band {v['band_mfu']})")
        print(f"# perfdiff: {rep['verdict']} — {rep['compared']} compared, "
              f"{rep['flat']} flat / {rep['improved']} improved / "
              f"{rep['regressed']} regressed"
              + (f" {rep['regressed_legs']}" if rep["regressed_legs"]
                 else ""))
    if args.strict and rep["regressed"]:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
