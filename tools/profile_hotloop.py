#!/usr/bin/env python
"""Where does config1's per-frame time go on the real chip?

Measures, in order of increasing framework involvement:
  a) batch-1 device step time (device-resident input, sync each call)
  b) jit dispatch rate from Python (async, same input, drain at end)
  c) host->device invoke chain (numpy arg per call, flat wire, drain at end)
  d) backend.invoke() loop (JaxBackend, no graph)
  e) full streaming pipeline (DataSrc -> transform(fused) -> filter -> sink)
  f) (e) under cProfile, top cumulative entries

Run:  python tools/profile_hotloop.py [n_frames]
"""
import cProfile
import io
import os
import pstats
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # under the axon sitecustomize the env var alone does NOT stop the
    # accelerator plugin from dialing a (possibly wedged) tunnel at first
    # backend use; only the config API pins CPU reliably
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp


def rate(fn, n, drain=None):
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn()
    if drain is not None:
        drain(out)
    dt = time.perf_counter() - t0
    return n / dt, dt / n * 1e3


def tiny_main(n=1000):
    """Framework-overhead view: a near-zero-compute model makes the loop
    time ≈ pure framework cost (graph hops + backend.invoke + dispatch),
    the number VERDICT r4 'next' #3 bounds at ≤0.5 ms/frame.  Compute and
    transfer are ~0 here, so every millisecond is ours."""
    import numpy as np

    from nnstreamer_tpu.backends.jax_backend import JaxBackend, JaxModel
    from nnstreamer_tpu.spec import TensorSpec, TensorsSpec

    model = JaxModel(
        apply=lambda p, x: x.reshape(-1)[:8].astype(jnp.float32),
        input_spec=TensorsSpec.of(
            TensorSpec(dtype=np.uint8, shape=(224, 224, 3))),
    )
    img = np.random.default_rng(0).integers(0, 256, (224, 224, 3)).astype(np.uint8)
    frames = [img.copy() for _ in range(n)]

    fn = jax.jit(lambda x: x.reshape(-1)[:8].astype(jnp.float32))
    fn(img.reshape(-1)).block_until_ready()
    it = iter(frames)
    fps, ms = rate(lambda: fn(next(it).reshape(-1)), n,
                   drain=lambda o: o.block_until_ready())
    print(f"t0) raw jit dispatch:       {ms:8.4f} ms  ({fps:8.1f}/s)")

    be = JaxBackend()
    be.open(model)
    be.reconfigure(TensorsSpec.from_arrays((img,)))
    be.invoke((img,))
    it = iter(frames)
    fps, ms = rate(lambda: be.invoke((next(it),)), n,
                   drain=lambda o: o[0].block_until_ready())
    print(f"t1) backend.invoke loop:    {ms:8.4f} ms  ({fps:8.1f}/s)")

    import nnstreamer_tpu as nns
    from nnstreamer_tpu.elements.filter import TensorFilter
    from nnstreamer_tpu.elements.sink import TensorSink
    from nnstreamer_tpu.elements.testsrc import DataSrc

    state = {"first": None, "count": 0}

    def cb(frame):
        state["count"] += 1
        if state["first"] is None:
            state["first"] = time.perf_counter()

    best = None
    for _ in range(3):  # warm + take the best of three runs
        state.update(first=None, count=0)
        p = nns.Pipeline()
        src = p.add(DataSrc(data=frames))
        filt = p.add(TensorFilter(framework="jax", model=model))
        sink = p.add(TensorSink(callback=cb))
        p.link_chain(src, filt, sink)
        p.run(timeout=300)
        if state["first"] is None or state["count"] < 2:
            raise RuntimeError(
                f"pipeline delivered {state['count']} frames (need >= 2 "
                "for a rate) — stalled, or run with a larger n")
        dt = (time.perf_counter() - state["first"]) / (state["count"] - 1) * 1e3
        best = dt if best is None else min(best, dt)
    print(f"t2) full pipeline/frame:    {best:8.4f} ms  ({1e3 / best:8.1f}/s)")
    verdict = "PASS" if best <= 0.5 else "FAIL"
    print(f"t3) framework overhead budget (<=0.5 ms/frame): {verdict}")

    pr = cProfile.Profile()
    state.update(first=None, count=0)
    p = nns.Pipeline()
    src = p.add(DataSrc(data=frames))
    filt = p.add(TensorFilter(framework="jax", model=model))
    sink = p.add(TensorSink(callback=cb))
    p.link_chain(src, filt, sink)
    pr.enable()
    p.run(timeout=300)
    pr.disable()
    s = io.StringIO()
    st = pstats.Stats(pr, stream=s)
    st.sort_stats("tottime").print_stats(18)
    print(s.getvalue())


def main():
    if "--tiny" in sys.argv:
        args = [a for a in sys.argv[1:] if a != "--tiny"]
        tiny_main(int(args[0]) if args else 1000)
        return
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 200
    from nnstreamer_tpu.models import mobilenet_v2

    model = mobilenet_v2.build(num_classes=1001, image_size=224)
    rng = np.random.default_rng(0)
    img = rng.integers(0, 256, (224, 224, 3)).astype(np.uint8)
    flat = np.ascontiguousarray(img).reshape(-1)

    fused = jax.jit(lambda x: model.apply(
        model.params,
        ((x.astype(jnp.float32) - 127.5) / 127.5).reshape(1, 224, 224, 3),
    ))
    d = jax.device_put(flat)
    d.block_until_ready()
    fused(d).block_until_ready()
    fused(flat).block_until_ready()

    # a) sync step time, device-resident
    fps, ms = rate(lambda: fused(d).block_until_ready(), min(n, 100))
    print(f"a) sync device step:        {ms:8.3f} ms  ({fps:7.1f}/s)")

    # b) async dispatch, device-resident
    fps, ms = rate(lambda: fused(d), n, drain=lambda o: o.block_until_ready())
    print(f"b) async dispatch (device): {ms:8.3f} ms  ({fps:7.1f}/s)")

    # c) async chain from host numpy (fresh array each call to defeat caching)
    frames = [flat.copy() for _ in range(n)]
    it = iter(frames)
    fps, ms = rate(lambda: fused(next(it)), n, drain=lambda o: o.block_until_ready())
    print(f"c) async chain (host np):   {ms:8.3f} ms  ({fps:7.1f}/s)")

    # c2) explicit device_put then dispatch, K-deep window
    it = iter(frames)
    fps, ms = rate(lambda: fused(jax.device_put(next(it))), n,
                   drain=lambda o: o.block_until_ready())
    print(f"c2) device_put + dispatch:  {ms:8.3f} ms  ({fps:7.1f}/s)")

    # d) backend.invoke loop (float32 frames — the model's declared spec;
    # the streaming pipeline feeds uint8 only via the fused-transform entry)
    from nnstreamer_tpu.backends.jax_backend import JaxBackend
    from nnstreamer_tpu.spec import TensorsSpec

    imgf = img.astype(np.float32)
    be = JaxBackend()
    be.open(model)
    be.reconfigure(TensorsSpec.from_arrays((imgf,)))
    be.invoke((imgf,))
    frames2 = [imgf.copy() for _ in range(n)]
    it2 = iter(frames2)
    fps, ms = rate(lambda: be.invoke((next(it2),)), n,
                   drain=lambda o: o[0].block_until_ready())
    print(f"d) backend.invoke loop:     {ms:8.3f} ms  ({fps:7.1f}/s)")

    # e) full pipeline
    import bench

    data = [img.copy() for _ in range(n)]
    fps = bench.run_pipeline_fps("jax", model, data)
    print(f"e) full pipeline:           {1e3 / fps:8.3f} ms  ({fps:7.1f}/s)")

    # f) profile the pipeline run
    pr = cProfile.Profile()
    pr.enable()
    fps = bench.run_pipeline_fps("jax", model, data)
    pr.disable()
    print(f"f) pipeline under profile:  {1e3 / fps:8.3f} ms  ({fps:7.1f}/s)")
    s = io.StringIO()
    st = pstats.Stats(pr, stream=s)
    st.sort_stats("cumulative").print_stats(30)
    print(s.getvalue())


if __name__ == "__main__":
    main()
