#!/usr/bin/env python
"""Where does config1's per-frame time go on the real chip?

Measures, in order of increasing framework involvement:
  a) batch-1 device step time (device-resident input, sync each call)
  b) jit dispatch rate from Python (async, same input, drain at end)
  c) host->device invoke chain (numpy arg per call, flat wire, drain at end)
  d) backend.invoke() loop (JaxBackend, no graph)
  e) full streaming pipeline (DataSrc -> transform(fused) -> filter -> sink)
  f) (e) under cProfile, top cumulative entries

Run:  python tools/profile_hotloop.py [n_frames]
"""
import cProfile
import io
import os
import pstats
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp


def rate(fn, n, drain=None):
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn()
    if drain is not None:
        drain(out)
    dt = time.perf_counter() - t0
    return n / dt, dt / n * 1e3


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 200
    from nnstreamer_tpu.models import mobilenet_v2

    model = mobilenet_v2.build(num_classes=1001, image_size=224)
    rng = np.random.default_rng(0)
    img = rng.integers(0, 256, (224, 224, 3)).astype(np.uint8)
    flat = np.ascontiguousarray(img).reshape(-1)

    fused = jax.jit(lambda x: model.apply(
        model.params,
        ((x.astype(jnp.float32) - 127.5) / 127.5).reshape(1, 224, 224, 3),
    ))
    d = jax.device_put(flat)
    d.block_until_ready()
    fused(d).block_until_ready()
    fused(flat).block_until_ready()

    # a) sync step time, device-resident
    fps, ms = rate(lambda: fused(d).block_until_ready(), min(n, 100))
    print(f"a) sync device step:        {ms:8.3f} ms  ({fps:7.1f}/s)")

    # b) async dispatch, device-resident
    fps, ms = rate(lambda: fused(d), n, drain=lambda o: o.block_until_ready())
    print(f"b) async dispatch (device): {ms:8.3f} ms  ({fps:7.1f}/s)")

    # c) async chain from host numpy (fresh array each call to defeat caching)
    frames = [flat.copy() for _ in range(n)]
    it = iter(frames)
    fps, ms = rate(lambda: fused(next(it)), n, drain=lambda o: o.block_until_ready())
    print(f"c) async chain (host np):   {ms:8.3f} ms  ({fps:7.1f}/s)")

    # c2) explicit device_put then dispatch, K-deep window
    it = iter(frames)
    fps, ms = rate(lambda: fused(jax.device_put(next(it))), n,
                   drain=lambda o: o.block_until_ready())
    print(f"c2) device_put + dispatch:  {ms:8.3f} ms  ({fps:7.1f}/s)")

    # d) backend.invoke loop (float32 frames — the model's declared spec;
    # the streaming pipeline feeds uint8 only via the fused-transform entry)
    from nnstreamer_tpu.backends.jax_backend import JaxBackend
    from nnstreamer_tpu.spec import TensorsSpec

    imgf = img.astype(np.float32)
    be = JaxBackend()
    be.open(model)
    be.reconfigure(TensorsSpec.from_arrays((imgf,)))
    be.invoke((imgf,))
    frames2 = [imgf.copy() for _ in range(n)]
    it2 = iter(frames2)
    fps, ms = rate(lambda: be.invoke((next(it2),)), n,
                   drain=lambda o: o[0].block_until_ready())
    print(f"d) backend.invoke loop:     {ms:8.3f} ms  ({fps:7.1f}/s)")

    # e) full pipeline
    import bench

    data = [img.copy() for _ in range(n)]
    fps = bench.run_pipeline_fps("jax", model, data)
    print(f"e) full pipeline:           {1e3 / fps:8.3f} ms  ({fps:7.1f}/s)")

    # f) profile the pipeline run
    pr = cProfile.Profile()
    pr.enable()
    fps = bench.run_pipeline_fps("jax", model, data)
    pr.disable()
    print(f"f) pipeline under profile:  {1e3 / fps:8.3f} ms  ({fps:7.1f}/s)")
    s = io.StringIO()
    st = pstats.Stats(pr, stream=s)
    st.sort_stats("cumulative").print_stats(30)
    print(s.getvalue())


if __name__ == "__main__":
    main()
