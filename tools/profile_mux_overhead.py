#!/usr/bin/env python
"""Why does CPU-fallback mux throughput DECLINE as streams are added?

VERDICT r5 item 4: `config5_scaling {1: 5.84 -> 8: 4.81}` — on the CPU
fallback the mux->batch->filter->unbatch->demux path LOSES aggregate
throughput per added stream, where batching should at worst be flat.
This tool isolates where the per-stream cost lands:

- sweeps STREAM COUNTS (1, 2, 4, 8 by default) at a fixed TOTAL frame
  budget, identity jax model, CPU pin — so the filter's work is constant
  and any decline is pure machinery;
- attributes wall time per element via the obs hook bus
  (``dispatch_exit`` carries wall-ns per sink-pad dispatch): mux collect
  vs batch concat vs filter invoke vs unbatch/demux fan-out;
- reports source/sink thread counts per config (each added stream adds a
  source thread and a sink dispatch — on a GIL'd 1-core host those time-
  slice rather than parallelize);
- accounts hot-path host memcpy via the ``copy`` hook (the zero-copy
  path's tracer signal, ``nnstreamer_tpu/pool.py``): bytes-copied and
  fresh allocations per frame ride as sweep-table columns, so the
  pooled slot-wise assembly / RowBatch concat-skip savings are visible
  next to the fps they buy;
- separates TRUE device time from host machinery via the device lane
  (``nnstreamer_tpu/obs/device.py``): a ``DeviceTracer`` completion
  probe per dispatch yields a ``dev us/fr`` column — on an async
  backend the ``dispatch_exit`` attribution only times the enqueue, so
  without this column device compute hides inside whichever element
  blocks first — and a ``hostdisp`` column: summed
  ``device_idle{reason=host_dispatch}`` span µs per frame (gaps where
  the chip sat starved with nothing enqueued — the dead time
  whole-segment compilation folds away, docs/performance.md);
- rides the cost observatory (``nnstreamer_tpu/obs/costmodel.py``)
  over every measured run: ``cm disp`` / ``cm qwait`` columns are the
  summed per-stage mean host-dispatch and queue-wait µs from the same
  per-leg aggregates the ``costmodel`` tracer persists to
  COST_MODEL.json — the sweep table and the persisted model can be
  cross-checked against each other;
- shows UTILIZATION, not just latency (the obs/util.py lane): ``mfu``
  (cost_analysis flops over measured device time vs the configured
  peak) and ``busy`` (windowed device_exec coverage per device)
  columns ride the same sweep, so "8 streams decline" separates into
  "chip idle" vs "chip busy on machinery".

Usage: ``python tools/profile_mux_overhead.py [--mesh[=SPEC]] [--ttff]
[--lanes[=N]] [TOTAL_FRAMES] [SWEEP...]`` e.g. ``python
tools/profile_mux_overhead.py 2000 1 2 4 8 16 32 64``.  ``--mesh``
(default spec ``dp:8``) sweeps the mesh-sharded dispatch lane over a
forced 8-device host mesh and adds chips-used / per-shard-batch
columns.  ``--ttff`` prints cold-vs-warm time-to-first-frame columns
instead of the sweep: two fresh processes against one persistent
executable cache (``[compile] cache_dir`` + warmup), the warm row gated
on zero compile misses.  ``--lanes`` (default ``auto``) runs the sweep
on the dispatcher-lane runtime (``graph/lanes.py``) instead of
thread-per-element; either way a ``lanes`` column reports the mode and
the run ends with a lane-vs-thread A/B at the widest point (the other
mode re-measured) plus the 8→widest flatness verdict — thread mode
multiplies host threads per stream and declines, lanes must hold the
widest point within ~10% of the 8-stream point.
``NNSTPU_POOL_ENABLED=false NNSTPU_POOL_CONCAT_THRESHOLD=0`` reproduces
the pre-pool behavior for an A/B.  Appends nothing; copy the table +
verdict into BENCH_NOTES.md.
"""
import os
import sys
import threading
import time
from collections import defaultdict

_T0 = time.perf_counter()  # process start for the --ttff-child probe

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# --ttff: cold-vs-warm time-to-first-frame columns (process start →
# first sink frame) — the compile-ahead lane's proof, run as two fresh
# child processes against one persistent executable cache.
TTFF = False
TTFF_CHILD = False
for _arg in list(sys.argv):
    if _arg == "--ttff":
        TTFF = True
        sys.argv.remove(_arg)
    elif _arg == "--ttff-child":
        TTFF_CHILD = True
        sys.argv.remove(_arg)

# --lanes[=N|auto]: run the sweep on the dispatcher-lane runtime
# ([dispatch] lanes); the A/B verdict at the end measures the other mode
LANES = None
for _arg in list(sys.argv):
    if _arg == "--lanes" or _arg.startswith("--lanes="):
        LANES = _arg.partition("=")[2] or "auto"
        sys.argv.remove(_arg)

# --mesh[=SPEC] (default dp:8): sweep the mesh-sharded dispatch lane —
# must export NNSTPU_MESH and the forced host device count BEFORE jax
# initializes its CPU client
MESH = None
for _arg in list(sys.argv):
    if _arg == "--mesh" or _arg.startswith("--mesh="):
        MESH = _arg.partition("=")[2] or "dp:8"
        sys.argv.remove(_arg)
if MESH is not None:
    os.environ["NNSTPU_MESH"] = MESH
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8").strip()

# the per-run cost-model tracers are sweep probes, not evidence: they
# must not write COST_MODEL.json on every stop (explicit env wins)
os.environ.setdefault("NNSTPU_OBS_COSTMODEL_AUTOSAVE", "false")
# the hostdisp column prices every starvation gap ≥50 µs — the default
# 5 ms floor is tuned for alerting, not for a µs-scale identity sweep
os.environ.setdefault("NNSTPU_OBS_DEVICE_IDLE_GAP_MS", "0.05")

import jax

jax.config.update("jax_platforms", "cpu")
import numpy as np

from nnstreamer_tpu import Pipeline
from nnstreamer_tpu.backends.jax_backend import JaxModel
from nnstreamer_tpu.elements.batch import TensorBatch, TensorUnbatch
from nnstreamer_tpu.elements.demux import TensorDemux
from nnstreamer_tpu.elements.filter import TensorFilter
from nnstreamer_tpu.elements.mux import TensorMux
from nnstreamer_tpu.elements.queue import Queue
from nnstreamer_tpu.elements.sink import TensorSink
from nnstreamer_tpu.elements.testsrc import DataSrc
from nnstreamer_tpu.obs import hooks
from nnstreamer_tpu.obs import spans as obs_spans
from nnstreamer_tpu.obs.costmodel import CostModelTracer
from nnstreamer_tpu.obs.device import DeviceTracer
from nnstreamer_tpu.obs.metrics import MetricsRegistry
from nnstreamer_tpu.spec import TensorSpec, TensorsSpec

TOTAL = int(sys.argv[1]) if len(sys.argv) > 1 else 2000
SWEEP = [int(a) for a in sys.argv[2:]] or [1, 2, 4, 8, 16, 32, 64]
# identity isolates the collect/batch machinery; matmul emulates the
# compute-bound config5 regime (is the decline machinery or model?)
MODEL = os.environ.get("MUX_PROFILE_MODEL", "identity")
D = int(os.environ.get("MUX_PROFILE_DIM",
                       "16" if MODEL == "identity" else "1024"))
arr = np.zeros((D,), np.float32)
_W = None


def model_for(streams):
    shape = (D,) if streams == 1 else (streams, D)
    spec = TensorsSpec.of(TensorSpec(dtype=np.float32, shape=shape))
    if MODEL == "identity":
        return JaxModel(apply=lambda p, x: x, input_spec=spec)
    global _W
    if _W is None:
        import jax.numpy as jnp

        _W = jnp.asarray(np.random.default_rng(0)
                         .standard_normal((D, D)).astype(np.float32))

    def apply(p, x):
        h = x
        for _ in range(8):  # ~8 * D^2 flops/frame: compute-bound on CPU
            h = jax.numpy.tanh(h @ _W)
        return h

    return JaxModel(apply=apply, input_spec=spec)


class Attribution:
    """Per-element busy wall-ns from the dispatch_exit hook."""

    def __init__(self):
        self.ns = defaultdict(int)
        self.calls = defaultdict(int)
        self._lock = threading.Lock()

    def __call__(self, node, pad, item, dur_ns):
        with self._lock:
            self.ns[type(node).__name__] += dur_ns
            self.calls[type(node).__name__] += 1

    def table(self):
        return sorted(self.ns.items(), key=lambda kv: -kv[1])


class CopyCount:
    """Hot-path host memcpy accounting from the ``copy`` hook."""

    def __init__(self):
        self.nbytes = 0
        self.copies = 0
        self.allocs = 0
        self._lock = threading.Lock()

    def __call__(self, node, nbytes, allocs):
        with self._lock:
            self.nbytes += int(nbytes)
            self.copies += 1
            self.allocs += int(allocs)


def run_mux(streams, frames_per_stream, attribute=False, lanes=None,
            wide=None):
    """One measured pipeline run.  ``lanes``: None = whatever the
    environment says, ``0`` = force thread-per-element, ``N``/``auto``
    = force the dispatcher-lane runtime.  ``wide`` forces the
    independent-chains topology regardless of stream count (used to
    anchor the flatness verdict within ONE topology)."""
    if lanes is not None:
        os.environ["NNSTPU_DISPATCH_LANES"] = str(lanes)
    use_wide = (streams > 16) if wide is None else bool(wide)
    state = {"count": 0, "t0": None}
    _cb_lock = threading.Lock()

    def cb(frame):
        with _cb_lock:
            if state["t0"] is None:
                state["t0"] = time.perf_counter()
            state["count"] += 1

    p = Pipeline()
    if streams == 1 and not use_wide:
        src = p.add(DataSrc(name="s0", data=[arr.copy() for _ in
                                             range(frames_per_stream)]))
        filt = p.add(TensorFilter(name="f", framework="jax",
                                  model=model_for(1)))
        sink = p.add(TensorSink(name="o0", callback=cb))
        p.link_chain(src, filt, sink)
    elif use_wide:
        # TensorMux caps at 16 sink pads, and past 16 streams the
        # question changes anyway: this is the fleet-worker regime —
        # N INDEPENDENT chains per host (src → queue → filter → sink),
        # where thread-per-element pays 2 threads per stream and the
        # dispatcher lanes pay none.  The filters are host-side
        # (framework=custom): what this regime measures is pure
        # scheduling machinery — per-chain jax backends would each
        # compile inside the measured window and drown it.
        filt = None
        for i in range(streams):
            src = p.add(DataSrc(name=f"s{i}", data=[
                arr.copy() for _ in range(frames_per_stream)]))
            qn = p.add(Queue(name=f"q{i}", max_size_buffers=16))
            fn = p.add(TensorFilter(name=f"f{i}", framework="custom",
                                    model=lambda x: x * 2.0))
            p.link_chain(src, qn, fn,
                         p.add(TensorSink(name=f"o{i}", callback=cb)))
            if filt is None:
                filt = fn
    else:
        mux = p.add(TensorMux(sync_mode="nosync"))
        for i in range(streams):
            src = p.add(DataSrc(name=f"s{i}", data=[arr.copy() for _ in
                                                    range(frames_per_stream)]))
            p.link(src, f"{mux.name}.sink_{i}")
        batch = p.add(TensorBatch())
        filt = p.add(TensorFilter(name="f", framework="jax",
                                  model=model_for(streams)))
        unb = p.add(TensorUnbatch())
        demux = p.add(TensorDemux())
        p.link_chain(mux, batch, filt, unb, demux)
        for i in range(streams):
            p.link(f"{demux.name}.src_{i}",
                   p.add(TensorSink(name=f"o{i}", callback=cb)))
    attr = Attribution()
    copies = CopyCount()
    obs_spans.reset()  # fresh recorder per run; the tracer re-activates
    dev = p.attach_tracer(DeviceTracer(registry=MetricsRegistry()))
    cm = p.attach_tracer(CostModelTracer(registry=MetricsRegistry()))
    hooks.connect("copy", copies)
    if attribute:
        hooks.connect("dispatch_exit", attr)
    nlanes = 0
    host_threads = 0
    try:
        t_start = time.perf_counter()
        p.start()
        nlanes = p._lanes.nlanes if p._lanes is not None else 0
        # threads the graph OWNS (spawned sources/workers, or lanes +
        # promoted helpers) — active_count() would under-count fast
        # finite sources that exit before the sweep ends
        if p._lanes is not None:
            host_threads = nlanes + len(p._lanes._helpers)
        else:
            host_threads = len(p.threads)
        if not p.wait(600):
            raise RuntimeError("sweep pipeline did not finish")
        p.stop()
        wall = time.perf_counter() - t_start
    finally:
        hooks.disconnect("copy", copies)
        if attribute:
            hooks.disconnect("dispatch_exit", attr)
    done = state["count"] - max(1, streams)  # exclude the clock-start frame(s)
    fps = done / (time.perf_counter() - state["t0"])
    copies.t_first = state["t0"]  # absolute first-frame ts (--ttff-child)
    total_in = streams * frames_per_stream
    copies.per_frame = copies.nbytes / max(1, total_in)
    copies.allocs_per_frame = copies.allocs / max(1, total_in)
    # stop() drained the completion-probe queue: summary is final
    dsum = dev.summary()
    copies.dev_us_per_frame = dsum["device_ns"] / 1e3 / max(1, total_in)
    copies.dev_dispatches = dsum["completed"]
    # host-dispatch starvation: device_idle spans whose gap began with an
    # empty probe queue — dead time between device programs that
    # whole-segment compilation (graph/segments.py) exists to remove
    idle = [r for r in obs_spans.snapshot()
            if r[0] == obs_spans.PH_COMPLETE and r[4] == "device_idle"
            and r[9].get("reason") == "host_dispatch"]
    copies.hostdisp_us = sum(r[2] for r in idle) / 1e3 / max(1, total_in)
    # utilization columns (obs/util.py lane): aggregate MFU and mean
    # busy fraction across the devices this config touched — so the
    # 1→8 stream sweep shows whether added streams buy chip utilization
    # or only host machinery (mfu None = no cost_analysis on this host)
    devs = list(dsum["by_device"].values())
    mfus = [d["mfu"] for d in devs if d.get("mfu") is not None]
    copies.mfu = sum(mfus) / len(mfus) if mfus else None
    busys = [d["busy_fraction"] for d in devs
             if d.get("busy_fraction") is not None]
    copies.busy = sum(busys) / len(busys) if busys else None
    # mesh columns: chips the LAST compiled executable actually spanned
    # (an indivisible leading dim falls back to 1) and the per-shard rows
    mesh = getattr(filt.backend, "_mesh", None)
    copies.chips = int(mesh.devices.size) if mesh is not None else 1
    copies.per_shard = max(1, streams) / copies.chips
    copies.lanes = nlanes
    copies.host_threads = host_threads
    # cost-model columns (obs/costmodel.py): the same per-stage legs
    # the observatory persists, summed across nodes — mean host-dispatch
    # and queue-wait µs per event, next to the fps they explain
    cm_stages = cm.summary()["stages"]

    def _leg_sum(leg):
        vals = [st["legs"][leg]["mean_us"] for st in cm_stages.values()
                if leg in st["legs"]]
        return sum(vals) if vals else None

    copies.cm_dispatch_us = _leg_sum("dispatch")
    copies.cm_queue_us = _leg_sum("queue_wait")
    return fps, wall, attr, copies


def ttff_child() -> None:
    """One cold/warm probe leg: 4-stream mux pipeline, JSON line out
    (``ttff_s`` = process start → first sink frame)."""
    import json

    from nnstreamer_tpu.obs.metrics import REGISTRY

    _, _, _, cp = run_mux(4, 8)
    c = REGISTRY.get("nnstpu_compile_total")
    compiles = ({k[0]: int(v.value) for k, v in dict(c.children()).items()}
                if c else {})
    print(json.dumps({"ttff_s": round(cp.t_first - _T0, 4),
                      "compiles": compiles}))


def ttff_sweep() -> None:
    """Cold-vs-warm TTFF columns: the same pipeline in two fresh
    processes against one persistent executable cache ([compile]
    cache_dir).  The warm row must show zero compile misses."""
    import json
    import shutil
    import subprocess
    import tempfile

    cache = tempfile.mkdtemp(prefix="nns_mux_ttff_")
    try:
        env = dict(os.environ,
                   NNSTPU_COMPILE_CACHE_DIR=cache,
                   NNSTPU_COMPILE_WARMUP="1")
        print(f"{'run':>6} {'ttff s':>8} {'miss':>6} {'persist_hit':>12}")
        rows = {}
        for label in ("cold", "warm"):
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--ttff-child"],
                env=env, capture_output=True, text=True, timeout=600)
            if proc.returncode != 0:
                print(f"{label}: FAILED\n{proc.stderr[-400:]}")
                return
            child = json.loads(proc.stdout.strip().splitlines()[-1])
            rows[label] = child
            c = child["compiles"]
            print(f"{label:>6} {child['ttff_s']:>8.3f} "
                  f"{c.get('miss', 0):>6} {c.get('persist_hit', 0):>12}")
        misses = rows["warm"]["compiles"].get("miss", 0)
        speedup = rows["cold"]["ttff_s"] / max(rows["warm"]["ttff_s"], 1e-9)
        verdict = ("zero cold-start OK" if misses == 0
                   else "COLD COMPILES ON THE REQUEST PATH")
        print(f"warm misses = {misses} ({verdict}); "
              f"ttff speedup = {speedup:.2f}x")
    finally:
        shutil.rmtree(cache, ignore_errors=True)


def main():
    if TTFF_CHILD:
        ttff_child()
        return
    if TTFF:
        ttff_sweep()
        return
    ncpu = os.cpu_count()
    mode_lanes = LANES if LANES is not None else 0
    print(f"mux overhead sweep: total={TOTAL} frames, host cpus={ncpu}, "
          f"mode={'lanes=' + str(mode_lanes) if LANES is not None else 'thread-per-element'}")
    if MESH is not None:
        print(f"mesh-sharded dispatch: NNSTPU_MESH={MESH!r} over "
              f"{len(jax.devices())} host devices")
    def fmt_mfu(v):
        return f"{v * 100:>8.3f}%" if v is not None else f"{'-':>9}"

    def fmt_busy(v):
        return f"{v * 100:>6.1f}%" if v is not None else f"{'-':>7}"

    def fmt_cm(v):
        return f"{v:>9.1f}" if v is not None else f"{'-':>9}"

    run_mux(1, 50, lanes=mode_lanes)
    base_fps, _, _, base_cp = run_mux(1, TOTAL, lanes=mode_lanes)
    print(f"\n{'streams':>7} {'lanes':>6} {'agg fps':>10} {'us/frame':>10} "
          f"{'vs 1-stream':>11} {'copy KB/fr':>11} {'allocs/fr':>10} "
          f"{'dev us/fr':>10} {'hostdisp':>9} {'mfu':>9} {'busy':>7} "
          f"{'chips':>6} {'b/shard':>8} {'cm disp':>9} {'cm qwait':>9}")
    print(f"{1:>7} {base_cp.lanes:>6} {base_fps:>10.0f} "
          f"{1e6 / base_fps:>10.1f} {'1.00x':>11} "
          f"{base_cp.per_frame / 1024:>11.1f} "
          f"{base_cp.allocs_per_frame:>10.3f} "
          f"{base_cp.dev_us_per_frame:>10.1f} "
          f"{base_cp.hostdisp_us:>9.1f} "
          f"{fmt_mfu(base_cp.mfu)} {fmt_busy(base_cp.busy)} "
          f"{base_cp.chips:>6} {base_cp.per_shard:>8.2f} "
          f"{fmt_cm(base_cp.cm_dispatch_us)} {fmt_cm(base_cp.cm_queue_us)}")
    results = {1: base_fps}
    last_cp = base_cp
    for s in [s for s in SWEEP if s != 1]:
        run_mux(s, max(8, 160 // s), lanes=mode_lanes)  # warm the s-wide exe
        fps, _, _, cp = run_mux(s, TOTAL // s, lanes=mode_lanes)
        results[s] = fps
        last_cp = cp
        print(f"{s:>7} {cp.lanes:>6} {fps:>10.0f} {1e6 / fps:>10.1f} "
              f"{fps / base_fps:>10.2f}x {cp.per_frame / 1024:>11.1f} "
              f"{cp.allocs_per_frame:>10.3f} {cp.dev_us_per_frame:>10.1f} "
              f"{cp.hostdisp_us:>9.1f} "
              f"{fmt_mfu(cp.mfu)} {fmt_busy(cp.busy)} "
              f"{cp.chips:>6} {cp.per_shard:>8.2f} "
              f"{fmt_cm(cp.cm_dispatch_us)} {fmt_cm(cp.cm_queue_us)}")

    # lane-vs-thread A/B at the widest point: re-measure in the OTHER
    # mode, then judge flatness per mode — widest vs the 8-stream point
    # measured in the SAME topology (past 16 streams the sweep switches
    # to independent chains, so the anchor is re-run wide too)
    widest = max(SWEEP)
    other = 0 if LANES is not None else "auto"
    run_mux(widest, max(8, 160 // widest), lanes=other)
    ab_fps, _, _, ab_cp = run_mux(widest, TOTAL // widest, lanes=other)
    this_label = f"lanes={mode_lanes}" if LANES is not None else "threads"
    other_label = "threads" if LANES is not None else f"lanes({ab_cp.lanes})"
    this_threads = last_cp.host_threads
    print(f"\nA/B at {widest} streams: {this_label} {results[widest]:.0f} "
          f"fps on {this_threads} host threads vs {other_label} "
          f"{ab_fps:.0f} fps on {ab_cp.host_threads} host threads "
          f"({results[widest] / max(ab_fps, 1e-9):.2f}x fps, "
          f"{ab_cp.host_threads / max(this_threads, 1)}x the threads)")
    if widest > 16:
        wide = widest > 16
        run_mux(8, 20, lanes=mode_lanes, wide=wide)
        anchor, _, _, _ = run_mux(8, TOTAL // 8, lanes=mode_lanes,
                                  wide=wide)
        run_mux(8, 20, lanes=other, wide=wide)
        anchor_ab, _, _, _ = run_mux(8, TOTAL // 8, lanes=other, wide=wide)
    else:
        anchor = anchor_ab = results.get(8) or results[
            min(results, key=lambda k: abs(k - 8))]
    flat = results[widest] / max(anchor, 1e-9)
    flat_ab = ab_fps / max(anchor_ab, 1e-9)
    if LANES is not None:
        verdict = "FLAT (within 10%)" if flat >= 0.90 else "DECLINING"
        print(f"lane flatness: {widest}-stream agg is {flat:.2f}x the "
              f"8-stream point (same topology) -> {verdict}; thread mode: "
              f"{flat_ab:.2f}x its own 8-stream point")
    else:
        print(f"thread flatness: {widest}-stream agg is {flat:.2f}x the "
              f"8-stream point (same topology); lane mode: {flat_ab:.2f}x "
              f"its own 8-stream point")

    # attribution pass at the widest sweep point (sweep mode)
    run_mux(widest, 30, lanes=mode_lanes)
    fps, wall, attr, cp = run_mux(widest, TOTAL // widest, attribute=True,
                                  lanes=mode_lanes)
    print(f"\nper-element busy time at {widest} streams "
          f"({TOTAL // widest} frames/stream, wall {wall:.2f}s; "
          "dispatch_exit hook, sink-pad wall-ns):")
    total_busy = sum(attr.ns.values()) or 1
    for name, ns in attr.table():
        per_call = ns / max(1, attr.calls[name]) / 1e3
        print(f"  {name:<14} {ns / 1e9:>8.3f}s  {100 * ns / total_busy:>5.1f}%"
              f"  {per_call:>8.1f} us/dispatch  x{attr.calls[name]}")
    busy_frac = total_busy / 1e9 / wall
    print(f"  busy/wall = {busy_frac:.2f} "
          f"(the rest is source threads + queue waits + GIL slicing)")
    print(f"  hot-path copies at {widest} streams: "
          f"{cp.per_frame / 1024:.1f} KB/frame, "
          f"{cp.allocs_per_frame:.3f} fresh allocs/frame "
          f"({cp.copies} memcpys, {cp.nbytes / 1e6:.1f} MB total)")
    print(f"  true device time at {widest} streams: "
          f"{cp.dev_us_per_frame:.1f} us/frame over {cp.dev_dispatches} "
          f"probed dispatches (device lane; host attribution above times "
          f"the enqueue only)")
    print(f"  host-dispatch starvation at {widest} streams: "
          f"{cp.hostdisp_us:.1f} us/frame of device_idle with an empty "
          f"probe queue (the gap whole-segment compilation folds away; "
          f"docs/performance.md)")
    mfu_s = f"{cp.mfu * 100:.3f}%" if cp.mfu is not None \
        else "n/a (no cost_analysis)"
    busy_s = f"{cp.busy * 100:.1f}%" if cp.busy is not None else "n/a"
    print(f"  utilization at {widest} streams: mfu {mfu_s}, device busy "
          f"fraction {busy_s} (the rest of the device window is idle — "
          f"host dispatch, queue wait, or wire; see device_idle spans)")


if __name__ == "__main__":
    main()
