#!/usr/bin/env python
"""Per-frame overhead of the mux->batch->filter->unbatch->demux path
vs a single stream, identity model, CPU: isolates the collect/batch
machinery cost that config5 adds."""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

from nnstreamer_tpu import Pipeline
from nnstreamer_tpu.backends.jax_backend import JaxModel
from nnstreamer_tpu.elements.batch import TensorBatch, TensorUnbatch
from nnstreamer_tpu.elements.demux import TensorDemux
from nnstreamer_tpu.elements.filter import TensorFilter
from nnstreamer_tpu.elements.mux import TensorMux
from nnstreamer_tpu.elements.sink import TensorSink
from nnstreamer_tpu.elements.testsrc import DataSrc
from nnstreamer_tpu.spec import TensorSpec, TensorsSpec

N = int(sys.argv[1]) if len(sys.argv) > 1 else 2000
STREAMS = 4
arr = np.zeros((16,), np.float32)

ident1 = JaxModel(apply=lambda p, x: x,
    input_spec=TensorsSpec.of(TensorSpec(dtype=np.float32, shape=(16,))))
identB = JaxModel(apply=lambda p, x: x,
    input_spec=TensorsSpec.of(TensorSpec(dtype=np.float32, shape=(STREAMS, 16))))

def run_single(n):
    state = {"count": 0, "t0": None}
    def cb(frame):
        if state["t0"] is None: state["t0"] = time.perf_counter()
        state["count"] += 1
    p = Pipeline()
    p.add(DataSrc(name="s", data=[arr.copy() for _ in range(n)]))
    p.add(TensorFilter(name="f", framework="jax", model=ident1))
    p.add(TensorSink(name="o", callback=cb))
    p.link_chain("s", "f", "o")
    p.run(timeout=300)
    return (state["count"] - 1) / (time.perf_counter() - state["t0"])

def run_mux(n_per_stream):
    state = {"count": 0, "t0": None}
    def cb(frame):
        if state["t0"] is None: state["t0"] = time.perf_counter()
        state["count"] += 1
    p = Pipeline()
    mux = p.add(TensorMux(sync_mode="nosync"))
    for i in range(STREAMS):
        src = p.add(DataSrc(name=f"s{i}", data=[arr.copy() for _ in range(n_per_stream)]))
        p.link(src, f"{mux.name}.sink_{i}")
    batch = p.add(TensorBatch())
    filt = p.add(TensorFilter(name="f", framework="jax", model=identB))
    unb = p.add(TensorUnbatch())
    demux = p.add(TensorDemux())
    p.link_chain(mux, batch, filt, unb, demux)
    for i in range(STREAMS):
        p.link(f"{demux.name}.src_{i}", p.add(TensorSink(name=f"o{i}", callback=cb)))
    p.run(timeout=300)
    return (state["count"] - STREAMS) / (time.perf_counter() - state["t0"])

run_single(50); run_mux(20)  # warm
fps1 = run_single(N)
print(f"single stream:  {1e6/fps1:8.1f} us/frame ({fps1:9.0f}/s)")
fpsM = run_mux(N // STREAMS)
print(f"mux x{STREAMS} batched: {1e6/fpsM:8.1f} us/frame ({fpsM:9.0f}/s aggregate)")
print(f"per-batched-invoke overhead: {STREAMS*1e6/fpsM:8.1f} us")
