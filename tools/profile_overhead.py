#!/usr/bin/env python
"""Framework-overhead microbench: identity model, CPU, tiny tensors.

Removes compute + transfer from the picture: what's left is the per-frame
cost of the graph runtime (pads, locks, frames, invoke plumbing).
Run under JAX_PLATFORMS=cpu.
"""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

from nnstreamer_tpu import Pipeline
from nnstreamer_tpu.backends.jax_backend import JaxModel
from nnstreamer_tpu.elements.filter import TensorFilter
from nnstreamer_tpu.elements.sink import TensorSink
from nnstreamer_tpu.elements.testsrc import DataSrc
from nnstreamer_tpu.elements.transform import TensorTransform
from nnstreamer_tpu.spec import TensorSpec, TensorsSpec

N = int(sys.argv[1]) if len(sys.argv) > 1 else 5000
arr = np.zeros((16,), np.float32)
frames = [arr.copy() for _ in range(N)]

model = JaxModel(
    apply=lambda p, x: x,
    input_spec=TensorsSpec.of(TensorSpec(dtype=np.float32, shape=(16,))),
)

def run(with_transform=False, profile=False):
    state = {"count": 0, "t0": None}
    def cb(frame):
        if state["t0"] is None: state["t0"] = time.perf_counter()
        state["count"] += 1
    p = Pipeline()
    src = p.add(DataSrc(data=frames))
    chain = [src]
    if with_transform:
        chain.append(p.add(TensorTransform(mode="arithmetic", option="add:0.0")))
    chain.append(p.add(TensorFilter(framework="jax", model=model)))
    chain.append(p.add(TensorSink(callback=cb)))
    p.link_chain(*chain)
    if profile:
        import cProfile, pstats, io
        pr = cProfile.Profile(); pr.enable()
    p.run(timeout=300)
    if profile:
        pr.disable()
        s = io.StringIO()
        pstats.Stats(pr, stream=s).sort_stats("tottime").print_stats(25)
        print(s.getvalue())
    dt = time.perf_counter() - state["t0"]
    return (state["count"] - 1) / dt

run(False)  # warm compile
fps = run(False)
print(f"src->filter->sink:            {1e6/fps:8.1f} us/frame ({fps:9.0f}/s)")
fps = run(True)
print(f"src->transform->filter->sink: {1e6/fps:8.1f} us/frame ({fps:9.0f}/s)")
if os.environ.get("PROFILE"):
    run(False, profile=True)
