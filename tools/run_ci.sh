#!/usr/bin/env bash
# Execute the EXACT steps of .github/workflows/ci.yml on this host and tee
# the transcript to CI_RUN_<date>.log — the executed-once proof the r3
# verdict asked for (row 42: config existed but had never run anywhere).
#
# Documented divergences from the YAML (everything else runs verbatim):
# - the dependency-install step is skipped (deps baked into this image;
#   `pip install` unavailable);
# - the driver-entry step pins jax to CPU via jax.config (this host's
#   axon sitecustomize ignores the env var; hosted runners don't);
# - BENCH_NOTES_PATH sends the smoke run's notes to /tmp so the real
#   BENCH_NOTES.md evidence isn't clobbered by tiny-frame numbers.
# Exit code 0 = the workflow would have passed.
set -uo pipefail
cd "$(dirname "$0")/.."
LOG="${1:-CI_RUN_$(date +%Y%m%d).log}"
: >"$LOG"

run_step() {
  local name="$1"; shift
  echo "=== STEP: $name ===" | tee -a "$LOG"
  local t0=$SECONDS
  if "$@" >>"$LOG" 2>&1; then
    echo "--- PASS (${name}, $((SECONDS - t0))s)" | tee -a "$LOG"
  else
    echo "--- FAIL (${name}, $((SECONDS - t0))s)" | tee -a "$LOG"
    echo "=== CI RESULT: FAIL ===" | tee -a "$LOG"
    exit 1
  fi
}

echo "ci run: $(date '+%Y-%m-%d %H:%M:%S') host=$(uname -sr) python=$(python -V 2>&1)" | tee -a "$LOG"

run_step "Build native runtime + C ABI (g++ smoke)" \
  python -c "from nnstreamer_tpu.native.capi import build_capi; print(build_capi())"

run_step "Run test suite with coverage gate" \
  env JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python tools/coverage_tool.py tests/ -q

run_step "Coverage floor check" python - <<'PY'
floor = 75.0
last = open("COVERAGE.txt").read().strip().splitlines()[-1]
pct = float(last.split()[-1].rstrip("%"))
print(f"coverage {pct:.1f}% (floor {floor}%)")
raise SystemExit(0 if pct >= floor else 1)
PY

run_step "Static analysis (nnslint contract gate: zero new findings)" \
  python tools/nnslint.py

run_step "Static analysis (lockdep smoke: seeded ABBA + cycle-clean pipeline)" \
  env NNSTPU_LOCKDEP=1 python - <<'PY'
import threading
import time

import jax
jax.config.update('jax_platforms', 'cpu')
import numpy as np

from nnstreamer_tpu import Pipeline
from nnstreamer_tpu.analysis import lockdep
from nnstreamer_tpu.elements.filter import TensorFilter
from nnstreamer_tpu.elements.queue import Queue
from nnstreamer_tpu.elements.sink import TensorSink
from nnstreamer_tpu.elements.testsrc import DataSrc

assert lockdep.installed(), "NNSTPU_LOCKDEP=1 did not install the verifier"

# 1) the detector detects: a seeded ABBA cycle must be reported
# (separate lines: lockdep keys locks by allocation site)
a = threading.Lock()
b = threading.Lock()
def ab():
    with a:
        with b:
            time.sleep(0.001)
def ba():
    with b:
        with a:
            time.sleep(0.001)
for fn in (ab, ba):
    t = threading.Thread(target=fn)
    t.start()
    t.join(timeout=30)
rep = lockdep.report()
assert len(rep["cycles"]) == 1, lockdep.format_report()

# 2) the runtime is clean: a real queue+filter pipeline (source thread,
# queue worker, dispatch chain, watchdoggable state machinery) must
# produce zero cycles and zero blocking-calls-under-lock
lockdep.reset()
got = []
p = Pipeline(name="ci_lockdep")
src = p.add(DataSrc(data=[np.full(4, i, np.float32) for i in range(16)],
                    name="s"))
q = p.add(Queue(max_size_buffers=8, name="q"))
filt = p.add(TensorFilter(framework="custom", model=lambda x: x * 2,
                          name="f"))
p.link_chain(src, q, filt, p.add(TensorSink(callback=got.append,
                                            name="out")))
p.run(timeout=120)
assert len(got) == 16, got
rep = lockdep.report()
assert rep["cycles"] == [], lockdep.format_report()
assert rep["blocking_calls"] == [], lockdep.format_report()

# 3) the dispatcher-lane runtime is clean too: the same pipeline on
# event-loop lanes (ready-rings, arm/run locks, helper promotion, the
# backpressure help path) must add its lock sites without a single new
# order cycle or blocking call under lock
import os
lockdep.reset()
os.environ["NNSTPU_DISPATCH_LANES"] = "2"
got2 = []
p2 = Pipeline(name="ci_lockdep_lanes")
src2 = p2.add(DataSrc(data=[np.full(4, i, np.float32) for i in range(16)],
                      name="s"))
q2 = p2.add(Queue(max_size_buffers=4, name="q"))
filt2 = p2.add(TensorFilter(framework="custom", model=lambda x: x * 2,
                            name="f"))
p2.link_chain(src2, q2, filt2, p2.add(TensorSink(callback=got2.append,
                                                 name="out")))
p2.run(timeout=120)
del os.environ["NNSTPU_DISPATCH_LANES"]
assert len(got2) == 16, got2
rep2 = lockdep.report()
assert rep2["cycles"] == [], lockdep.format_report()
assert rep2["blocking_calls"] == [], lockdep.format_report()

# 4) whole-segment compilation is clean: a jax filter with a decoder
# folded into its program (graph/segments.py — fusion install under the
# filter lock, undo closures on stop) must add no order cycle and no
# blocking call under lock
from nnstreamer_tpu.backends.jax_backend import JaxModel
from nnstreamer_tpu.elements.decoder import TensorDecoder
from nnstreamer_tpu.spec import TensorSpec, TensorsSpec

lockdep.reset()
W = np.random.default_rng(0).standard_normal((8, 10)).astype(np.float32)
seg_model = JaxModel(apply=lambda p, x: x @ W,
                     input_spec=TensorsSpec.of(
                         TensorSpec(dtype=np.float32, shape=(8,))))
got3 = []
p3 = Pipeline(name="ci_lockdep_seg")
p3.segment_compile = True
src3 = p3.add(DataSrc(data=[np.full(8, i, np.float32) for i in range(8)],
                      name="s"))
filt3 = p3.add(TensorFilter(framework="jax", model=seg_model, name="f"))
dec3 = p3.add(TensorDecoder(mode="image_labeling", name="d"))
p3.link_chain(src3, filt3, dec3, p3.add(TensorSink(callback=got3.append,
                                                   name="out")))
p3.run(timeout=120)
assert len(got3) == 8, got3
assert dec3.plugin._lowered is None, "segment fold not undone on stop"
rep3 = lockdep.report()
assert rep3["cycles"] == [], lockdep.format_report()
assert rep3["blocking_calls"] == [], lockdep.format_report()
print(f"lockdep smoke OK: seeded cycle detected, pipeline clean over "
      f"{rep['sites']} lock sites / {rep['edges']} order edges; lane "
      f"runtime clean over {rep2['sites']} sites / {rep2['edges']} edges; "
      f"segment-folded pipeline clean over {rep3['sites']} sites")
PY

# NOTE: on this host the axon sitecustomize makes the JAX_PLATFORMS env
# var insufficient (the workflow's plain env works on a hosted runner);
# jax.config.update before first backend use is the reliable local pin.
run_step "Driver entry points (compile check + multichip dryrun)" \
  env XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python -c "
import jax
jax.config.update('jax_platforms', 'cpu')
import __graft_entry__ as g
fn, args = g.entry()
print(jax.eval_shape(fn, *args))
g.dryrun_multichip(8)
print('dryrun OK')
"

run_step "Observability smoke (tracers + Prometheus scrape)" \
  env NNSTPU_TRACERS="latency;stats" NNSTPU_METRICS_PORT=0 \
  python - <<'PY'
import urllib.request

import jax
jax.config.update('jax_platforms', 'cpu')
import numpy as np

from nnstreamer_tpu import Pipeline
from nnstreamer_tpu.elements.sink import TensorSink
from nnstreamer_tpu.elements.testsrc import DataSrc
from nnstreamer_tpu.obs import export

got = []
p = Pipeline(name="ci_obs")
src = p.add(DataSrc(data=[np.full(4, i, np.float32) for i in range(8)]))
p.link(src, p.add(TensorSink(callback=got.append, name="out")))
p.run(timeout=120)
assert len(got) == 8, got

tr = p.stats()["tracers"]
(lat,), = (list(tr["latency"].values()),)
assert lat["count"] == 8, tr

server = export._server
assert server is not None, "NNSTPU_METRICS_PORT did not start the endpoint"
with urllib.request.urlopen(server.url, timeout=30) as resp:
    body = resp.read().decode("utf-8")
assert resp.status == 200 and body.strip(), "empty exposition"
assert "nnstpu_e2e_latency_ms_bucket" in body, body[:400]
assert "nnstpu_element_frames_total" in body, body[:400]
export.shutdown_server()
print(f"observability smoke OK: {len(body)} bytes of exposition, "
      f"e2e p99={lat['p99_ms']:.3f} ms")
PY

run_step "Tracing smoke (spans tracer + Chrome-trace export)" \
  env NNSTPU_TRACERS=spans \
  python - <<'PY'
import json

import jax
jax.config.update('jax_platforms', 'cpu')
import numpy as np

from nnstreamer_tpu import Pipeline
from nnstreamer_tpu.elements.filter import TensorFilter
from nnstreamer_tpu.elements.queue import Queue
from nnstreamer_tpu.elements.sink import TensorSink
from nnstreamer_tpu.elements.testsrc import DataSrc
from nnstreamer_tpu.obs import spans

got = []
p = Pipeline(name="ci_spans")
src = p.add(DataSrc(data=[np.full(4, i, np.float32) for i in range(8)],
                    name="s"))
q = p.add(Queue(max_size_buffers=8, name="q"))
filt = p.add(TensorFilter(framework="custom", model=lambda x: x * 2,
                          name="f"))
sink = p.add(TensorSink(callback=got.append, name="out"))
p.link_chain(src, q, filt, sink)
p.run(timeout=120)
assert len(got) == 8, got
assert all(spans.META_KEY in fr.meta for fr in got), \
    "trace context lost before the sink"

snap = p.flight_snapshot()
doc = json.loads(json.dumps(spans.chrome_trace(snap)))  # valid JSON
events = doc["traceEvents"]
xs = [e for e in events if e.get("ph") == "X"]
assert xs, "no complete spans recorded"

# nested dispatch spans: the filter's slice strictly contains the sink's
# on the queue worker thread
nested = any(
    a["tid"] == b["tid"] and a["name"] == "f" and b["name"] == "out"
    and a["ts"] <= b["ts"] and b["ts"] + b["dur"] <= a["ts"] + a["dur"] + 1e-6
    for a in xs for b in xs)
assert nested, "dispatch spans are not nested"

# at least one flow event pair crossing threads (src thread -> queue worker)
starts = {e["id"]: e for e in events if e.get("ph") == "s"}
cross = [e for e in events if e.get("ph") == "f"
         and e["id"] in starts and starts[e["id"]]["tid"] != e["tid"]]
assert cross, "no cross-thread flow event"

print(f"tracing smoke OK: {len(snap)} records, {len(xs)} spans, "
      f"{len(cross)} cross-thread flows; waterfall:")
print("\n".join(spans.waterfall(snap, limit=2).splitlines()[:8]))
PY

run_step "Device-obs smoke (device lane + compile counters + watchdog)" \
  env NNSTPU_TRACERS="latency,spans,device" NNSTPU_METRICS_PORT=0 \
      NNSTPU_OBS_FLIGHT_DUMP_DIR=/tmp/ci_device_obs_dumps \
  python - <<'PY'
import json
import os
import time
import urllib.error
import urllib.request

import jax
jax.config.update('jax_platforms', 'cpu')
import numpy as np

from nnstreamer_tpu import Frame, Pipeline
from nnstreamer_tpu.backends.jax_backend import JaxModel
from nnstreamer_tpu.elements.filter import TensorFilter
from nnstreamer_tpu.elements.sink import TensorSink
from nnstreamer_tpu.elements.testsrc import DataSrc
from nnstreamer_tpu.graph.node import SourceNode
from nnstreamer_tpu.obs import export, spans
from nnstreamer_tpu.obs.watchdog import PipelineWatchdog
from nnstreamer_tpu.spec import TensorSpec, TensorsSpec

model = JaxModel(apply=lambda p_, x: x * 2,
                 input_spec=TensorsSpec.of(
                     TensorSpec(dtype=np.float32, shape=(4,))))
got = []
p = Pipeline(name="ci_device")
src = p.add(DataSrc(data=[np.full(4, i, np.float32) for i in range(8)],
                    name="s"))
filt = p.add(TensorFilter(framework="jax", model=model, name="f"))
p.link_chain(src, filt, p.add(TensorSink(callback=got.append, name="out")))
p.run(timeout=120)
assert len(got) == 8, got
(dev,) = [t for t in p.tracers if t.name == "device"]
deadline = time.time() + 30
while time.time() < deadline and dev.summary()["completed"] < 8:
    time.sleep(0.05)
summ = dev.summary()
assert summ["completed"] == 8 and summ["dropped"] == 0, summ
assert summ["compiles"]["miss"] >= 1, summ

doc = json.loads(json.dumps(spans.chrome_trace(p.flight_snapshot())))
execs = [e for e in doc["traceEvents"]
         if e.get("ph") == "X" and e["name"] == "device_exec"]
assert len(execs) == 8, "no per-dispatch device_exec spans"
rows = {e["tid"]: e["args"]["name"] for e in doc["traceEvents"]
        if e.get("ph") == "M" and e["name"] == "thread_name"}
assert any(v.startswith("device:") for v in rows.values()), rows

server = export._server
assert server is not None, "NNSTPU_METRICS_PORT did not start the endpoint"
with urllib.request.urlopen(server.url, timeout=30) as resp:
    body = resp.read().decode("utf-8")
assert "nnstpu_device_exec_seconds_bucket" in body, body[:400]
assert 'nnstpu_compile_total{result="miss"}' in body, \
    [l for l in body.splitlines() if "compile" in l]
assert "nnstpu_device_dispatches_total" in body

# -- watchdog: a deliberately stalled source flips /healthz + dumps -----
class StallSrc(SourceNode):
    def output_spec(self):
        return TensorsSpec.of(TensorSpec(dtype=np.float32, shape=(4,)))
    def frames(self):
        yield Frame.of(np.zeros(4, np.float32))
        self._stop_evt.wait()

p2 = Pipeline(name="ci_stall")
p2.link(p2.add(StallSrc(name="cam")), p2.add(TensorSink(name="out")))
wd = p2.attach_tracer(PipelineWatchdog(interval_s=0.05, stall_s=0.2))
p2.start()
deadline = time.time() + 30
while time.time() < deadline and wd.summary()["healthy"]:
    time.sleep(0.05)
assert not wd.summary()["healthy"], wd.summary()
assert any("stalled_source:cam" in r for r in wd.summary()["reasons"])
try:
    urllib.request.urlopen(
        f"http://{server.host}:{server.port}/healthz", timeout=30)
    raise AssertionError("/healthz stayed 200 on a stalled pipeline")
except urllib.error.HTTPError as e:
    assert e.code == 503 and b"stalled_source:cam" in e.read()
dump = "/tmp/ci_device_obs_dumps/ci_stall.stall.trace.json"
assert os.path.exists(dump), "watchdog wrote no stall flight dump"
p2.stop()
export.shutdown_server()
print(f"device-obs smoke OK: {len(execs)} device_exec spans on "
      f"{[v for v in rows.values() if v.startswith('device:')]}, "
      f"compile misses={summ['compiles']['miss']}, watchdog flagged the "
      "stall and dumped flight data")
PY

run_step "Zero-copy smoke (pooled batch assembly + copies-per-frame gate)" \
  python - <<'PY'
import jax
jax.config.update('jax_platforms', 'cpu')
import numpy as np

from nnstreamer_tpu import Pipeline
from nnstreamer_tpu.backends.jax_backend import JaxModel
from nnstreamer_tpu.elements.batch import TensorBatch, TensorUnbatch
from nnstreamer_tpu.elements.demux import TensorDemux
from nnstreamer_tpu.elements.filter import TensorFilter
from nnstreamer_tpu.elements.mux import TensorMux
from nnstreamer_tpu.elements.sink import TensorSink
from nnstreamer_tpu.elements.testsrc import DataSrc
from nnstreamer_tpu.obs.metrics import MetricsRegistry
from nnstreamer_tpu.obs.tracers import CopiesTracer
from nnstreamer_tpu.pool import default_pool
from nnstreamer_tpu.spec import TensorSpec, TensorsSpec

STREAMS, FRAMES, DIM = 2, 100, 4096  # 16 KB rows, slot-wise pooled path
row = np.zeros((DIM,), np.float32)
model = JaxModel(apply=lambda p_, x: x,
                 input_spec=TensorsSpec.of(
                     TensorSpec(dtype=np.float32, shape=(STREAMS, DIM))))
count = [0]
p = Pipeline(name="ci_zerocopy")
mux = p.add(TensorMux(sync_mode="nosync"))
for i in range(STREAMS):
    src = p.add(DataSrc(name=f"s{i}",
                        data=[row.copy() for _ in range(FRAMES)]))
    p.link(src, f"{mux.name}.sink_{i}")
batch = p.add(TensorBatch())
filt = p.add(TensorFilter(name="f", framework="jax", model=model))
unb = p.add(TensorUnbatch())
demux = p.add(TensorDemux())
p.link_chain(mux, batch, filt, unb, demux)
for i in range(STREAMS):
    p.link(f"{demux.name}.src_{i}",
           p.add(TensorSink(name=f"o{i}",
                            callback=lambda fr: count.__setitem__(
                                0, count[0] + 1))))
tracer = p.attach_tracer(CopiesTracer(registry=MetricsRegistry()))
p.run(timeout=300)
assert count[0] == STREAMS * FRAMES, count

summ = tracer.summary()
row_bytes = row.nbytes
# copy-count regression gate: slot-wise assembly copies each source frame
# into the batch exactly ONCE (<= 1.05x payload bytes per frame), and the
# pool keeps fresh allocations to a handful of warmup leases — a new copy
# or allocation on this path fails CI before it costs throughput
budget = row_bytes * 1.05
assert summ["frames"] > 0
per_frame = summ["bytes_per_frame"]
assert per_frame <= budget, (per_frame, budget, summ)
assert summ["total_allocs"] <= 4, summ
st = default_pool().stats()
assert st["hits"] > 0, st  # the free list is actually being reused
print(f"zero-copy smoke OK: {per_frame / 1024:.1f} KB copied/frame "
      f"(budget {budget / 1024:.1f}), {summ['total_allocs']} fresh allocs "
      f"over {summ['frames']} frames, pool hits={st['hits']} "
      f"misses={st['misses']}")
PY

run_step "Scheduling smoke (DRR fairness + typed shed + live scrape)" \
  python - <<'PY'
import socket
import threading
import time
import urllib.request

import numpy as np

from nnstreamer_tpu.elements.query import (
    QueryOverloadError, QueryServer, recv_tensors, send_tensors)
from nnstreamer_tpu.obs.export import MetricsServer
from nnstreamer_tpu.obs.metrics import MetricsRegistry
from nnstreamer_tpu.sched import AdmissionController, Scheduler


def model(x):  # invoke cost proportional to rows
    time.sleep(0.002 * x.shape[0])
    return x * 2.0


def query(port, tensors):
    s = socket.create_connection(("127.0.0.1", port))
    try:
        send_tensors(s, tensors, 0)
        return recv_tensors(s)
    finally:
        s.close()


reg = MetricsRegistry()
sch = Scheduler("drr", quantum=8.0,
                admission=AdmissionController(max_queue=32),
                name="ci", registry=reg)
done, failures, shed = [], [], []
stop = threading.Event()
with QueryServer(framework="custom", model=model, batch=8,
                 batch_window_ms=5.0, scheduler=sch) as srv, \
        MetricsServer(port=0, registry=reg) as ms:

    def slow_flood():
        conns = [socket.create_connection(("127.0.0.1", srv.port))
                 for _ in range(3)]
        try:
            while not stop.is_set():
                for s in conns:
                    send_tensors(s, (np.ones((24, 4), np.float32),), 0)
                for s in conns:
                    recv_tensors(s)
        except (ConnectionError, OSError):
            pass
        finally:
            for s in conns:
                s.close()

    def fast(k):
        try:
            for i in range(8):
                out, _ = query(srv.port,
                               (np.full((1, 4), float(i), np.float32),))
                np.testing.assert_allclose(out[0], 2.0 * i)
            done.append(k)
        except Exception as exc:  # noqa: BLE001
            failures.append((k, exc))

    flood = threading.Thread(target=slow_flood, daemon=True)
    flood.start()
    time.sleep(0.1)
    fasts = [threading.Thread(target=fast, args=(k,)) for k in range(7)]
    for t in fasts:
        t.start()
    for t in fasts:
        t.join(timeout=120)
    stop.set()
    flood.join(timeout=30)
    assert not failures, failures
    assert len(done) == 7, done  # every fast client completed under flood
    # overload beyond admission limits sheds typed (zero hung conns)
    tight = Scheduler("fifo", admission=AdmissionController(max_queue=1),
                      name="ci_tight", registry=reg)
    with QueryServer(framework="custom", model=model,
                     scheduler=tight) as srv2:
        outcomes = []

        def burst():
            try:
                query(srv2.port, (np.ones((40, 4), np.float32),))
                outcomes.append("ok")
            except QueryOverloadError:
                outcomes.append("shed")

        bs = [threading.Thread(target=burst) for _ in range(3)]
        for t in bs:
            t.start()
        for t in bs:
            t.join(timeout=60)
        assert sorted(outcomes) == ["ok", "shed", "shed"], outcomes
    tight.close()
    with urllib.request.urlopen(ms.url, timeout=30) as resp:
        body = resp.read().decode("utf-8")
    assert "nnstpu_sched_queue_wait_ms_bucket" in body, body[:400]
    assert 'nnstpu_sched_dispatched_total{server="ci"}' in body
    assert ('nnstpu_sched_shed_total{server="ci_tight",reason="queue_full"'
            ',tenant="127.0.0.1"} 2') \
        in body, [l for l in body.splitlines() if "shed" in l]
st = srv.stats()["sched"]
sch.close()
print(f"scheduling smoke OK: {st['dispatched']} scheduled dispatches, "
      f"7/7 fast clients under flood, 2 typed sheds, live scrape carried "
      "nnstpu_sched_*")
PY

run_step "Chaos smoke (injected faults + self-healing + retrying client)" \
  env NNSTPU_FAULTS="seed=7;socket_drop@server:every=4,count=3;queue_wedge@cq:after=1,ms=1500" \
  python - <<'PY'
import time
import urllib.error
import urllib.request

import jax
jax.config.update('jax_platforms', 'cpu')
import numpy as np

from nnstreamer_tpu import Pipeline, faults
from nnstreamer_tpu.buffer import Frame
from nnstreamer_tpu.elements.query import QueryServer, TensorQueryClient
from nnstreamer_tpu.elements.queue import Queue
from nnstreamer_tpu.elements.sink import TensorSink
from nnstreamer_tpu.elements.testsrc import DataSrc
from nnstreamer_tpu.obs import export
from nnstreamer_tpu.obs.watchdog import PipelineWatchdog
from nnstreamer_tpu.spec import TensorSpec, TensorsSpec

VEC4 = TensorsSpec.of(TensorSpec(dtype=np.float32, shape=(4,)))

# -- 1: the server's reply socket is dropped mid-stream (a killed worker,
# as the client sees it); the retrying client must ride through to success
with QueryServer(framework="custom", model=lambda x: x * 2.0) as srv:
    cli = TensorQueryClient(host="127.0.0.1", port=srv.port, out_spec=VEC4,
                            request_timeout=30.0, retries=3,
                            retry_backoff_ms=10, name="chaos_cli")
    cli.start()
    for i in range(12):
        out = cli.process(
            None, Frame.of(np.full(4, float(i), np.float32), pts=i))
        np.testing.assert_allclose(np.asarray(out.tensor(0)), 2.0 * i)
eng = faults.engine()
drops = eng.injections.get("socket_drop", 0)
assert drops == 3, eng.stats()
assert cli.retries_total == drops, (cli.retries_total, drops)

# -- 2: a queue wedges under NNSTPU_FAULTS; the recovering watchdog must
# flag it (503), drain it, and /healthz must return to 200 in the window
server = export.ensure_server(0)
n = 60
got = []
p = Pipeline(name="chaos_ci")
src = p.add(DataSrc(data=[Frame.of(np.full(4, float(i), np.float32), pts=i)
                          for i in range(n)]))
q = p.add(Queue(max_size_buffers=200, name="cq"))
sink = p.add(TensorSink(name="out"))
sink.connect("new-data", lambda fr: got.append(fr.pts))
p.link_chain(src, q, sink)
p.attach_tracer(PipelineWatchdog(interval_s=0.05, stall_s=0.2,
                                 recover=True))
p.start()


def healthz():
    try:
        with urllib.request.urlopen(
                f"http://{server.host}:{server.port}/healthz",
                timeout=10) as r:
            return r.status
    except urllib.error.HTTPError as e:
        return e.code


deadline = time.time() + 30
while time.time() < deadline and healthz() != 503:
    time.sleep(0.02)
assert healthz() == 503, "watchdog never flagged the wedged queue"
assert p.wait(timeout=60), "pipeline did not reach EOS after recovery"
deadline = time.time() + 10
while time.time() < deadline and healthz() != 200:
    time.sleep(0.05)
code = healthz()
rec = p.recovery_stats()
p.stop()
export.shutdown_server()
assert code == 200, f"/healthz stuck at {code} after recovery"
assert rec["actions"].get("drain_queue", 0) >= 1, rec
assert len(got) + rec.get("shed_total", 0) == n, (len(got), rec)
print(f"chaos smoke OK: {drops} injected socket drops all retried to "
      f"success; watchdog drained the wedged queue (shed "
      f"{rec['shed_total']} typed), ledger balances "
      f"{len(got)}+{rec['shed_total']}=={n}, /healthz back to 200")
PY

run_step "Dispatcher-lane smoke (chaos soak on lanes: healthy end, exact ledger, byte-identical replay)" \
  env NNSTPU_DISPATCH_LANES=auto \
  python - <<'PY'
# The chaos-soak template (tests/test_soak.py) in lane mode: the
# run-to-completion runtime must ride a seeded raise+delay fault mix to
# a healthy EOS with the recovery ledger balancing EXACTLY and the
# fault engine's decision log replaying byte-identical — proof that
# supervised recovery and deterministic chaos are substrate-invariant.
import jax
jax.config.update('jax_platforms', 'cpu')
import numpy as np

from nnstreamer_tpu import Pipeline, faults
from nnstreamer_tpu.buffer import Frame
from nnstreamer_tpu.elements.filter import TensorFilter
from nnstreamer_tpu.elements.queue import Queue
from nnstreamer_tpu.elements.sink import TensorSink
from nnstreamer_tpu.elements.testsrc import DataSrc

n = 400
spec = "seed=1234;invoke_raise@f:rate=0.03;invoke_delay@f:rate=0.02,ms=1"
eng = faults.install(spec)
try:
    got = []
    p = Pipeline(name="ci_lane_soak")
    src = p.add(DataSrc(data=[
        Frame.of(np.full(4, float(i), np.float32), pts=i)
        for i in range(n)]))
    q = p.add(Queue(max_size_buffers=64, name="qsoak"))
    filt = p.add(TensorFilter(framework="custom",
                              model=lambda x: x * 2.0, name="f"))
    sink = p.add(TensorSink(name="out"))
    sink.connect("new-data",
                 lambda fr: got.append((fr.pts,
                                        float(np.asarray(fr.tensor(0))[0]))))
    p.link_chain(src, q, filt, sink)
    p.set_restart_policy("f", mode="restart", backoff_ms=1,
                         backoff_cap_ms=4, max_restarts=1000,
                         window_s=300.0)
    p.start()
    assert p._lanes is not None, "lane runtime did not activate"
    nlanes = p._lanes.nlanes
    assert p.wait(timeout=600)
    p.stop()

    raises = eng.injections.get("invoke_raise", 0)
    delays = eng.injections.get("invoke_delay", 0)
    assert raises > 0 and delays > 0, eng.stats()
    assert p.state == "STOPPED" and p._error is None
    rec = p.recovery_stats()
    assert rec["actions"]["restart_node"] == raises, rec
    assert rec["shed_total"] == raises, rec
    assert len(got) + rec["shed_total"] == n, (len(got), rec)
    assert [pts for pts, _ in got] == sorted(pts for pts, _ in got)
    for pts, val in got:
        assert val == 2.0 * pts, (pts, val)
    replay = faults.ChaosEngine(spec)
    for _ in range(n):
        replay.decide("backend_invoke", "f")
    assert replay.log == eng.log, "replay diverged from the live run"
    assert replay.injections == eng.injections
    print(f"lane smoke OK: {nlanes} lane(s), {len(got)} delivered + "
          f"{rec['shed_total']} typed shed == {n} offered, "
          f"{raises} restarts == {raises} injected raises, replay "
          f"byte-identical over {len(eng.log)} decisions")
finally:
    faults.deactivate()
PY

run_step "Mesh smoke (8-device host mesh: equivalence + per-chip spans)" \
  env NNSTPU_MESH=dp:8 XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      JAX_PLATFORMS=cpu \
  python - <<'PY'
import time

import jax
jax.config.update('jax_platforms', 'cpu')
import numpy as np

from nnstreamer_tpu import Pipeline
from nnstreamer_tpu.backends.jax_backend import JaxBackend, JaxModel
from nnstreamer_tpu.elements.dynbatch import DynBatch, DynUnbatch
from nnstreamer_tpu.elements.filter import TensorFilter
from nnstreamer_tpu.elements.sink import TensorSink
from nnstreamer_tpu.elements.testsrc import DataSrc
from nnstreamer_tpu.obs import spans
from nnstreamer_tpu.obs.device import DeviceTracer
from nnstreamer_tpu.obs.metrics import MetricsRegistry
from nnstreamer_tpu.parallel.mesh import dispatch_mesh_devices
from nnstreamer_tpu.spec import TensorsSpec

assert len(jax.devices()) == 8, jax.devices()
assert dispatch_mesh_devices() == 8

# -- sharded vs single-device equivalence on the raw backend ------------
w = (np.arange(16, dtype=np.float32).reshape(4, 4) / 7.0)
model = JaxModel(apply=lambda p, x: x @ p["w"] + 0.5, params={"w": w})
x = np.random.default_rng(7).standard_normal((16, 4)).astype(np.float32)
sharded = JaxBackend(); sharded.open(model)
sharded.reconfigure(TensorsSpec.from_arrays((x,)))
assert sharded._mesh is not None, "mesh did not activate"
(out,) = sharded.invoke((x,))
assert len(out.sharding.device_set) == 8, out.sharding
np.testing.assert_allclose(np.asarray(out), x @ w + 0.5, rtol=1e-5)

# -- dynbatch e2e over the mesh with the device lane attached -----------
got = []
mdl = JaxModel(apply=lambda p, x: x * 3.0, input_spec=None)
p = Pipeline(name="ci_mesh")
src = p.add(DataSrc(data=[np.full((4,), i, np.float32)
                          for i in range(24)], name="s"))
db = p.add(DynBatch(max_batch=8, name="db"))
filt = p.add(TensorFilter(framework="jax", model=mdl, name="f"))
un = p.add(DynUnbatch(name="un"))
p.link_chain(src, db, filt, un,
             p.add(TensorSink(callback=got.append, name="out")))
reg = MetricsRegistry()
dev = p.attach_tracer(DeviceTracer(registry=reg))
p.run(timeout=120)
assert len(got) == 24, len(got)
vals = sorted(float(f.tensors[0][0]) for f in got)
np.testing.assert_allclose(vals, [i * 3.0 for i in range(24)], rtol=1e-6)
deadline = time.time() + 30
while time.time() < deadline:
    s = dev.summary()
    if s["dispatches"] and s["completed"] == s["dispatches"]:
        break
    time.sleep(0.05)
summ = dev.summary()
assert summ["compiles"]["miss"] >= 1, summ

# nnstpu_device_exec spans on >= 2 device tracks (per-chip rows)
doc = spans.chrome_trace(p.flight_snapshot())
rows = {e["tid"]: e["args"]["name"] for e in doc["traceEvents"]
        if e.get("ph") == "M" and e["name"] == "thread_name"}
tracks = {rows[e["tid"]] for e in doc["traceEvents"]
          if e.get("ph") == "X" and e["name"] == "device_exec"}
dev_tracks = sorted(t for t in tracks if t.startswith("device:cpu:"))
assert len(dev_tracks) >= 2, tracks
assert len(summ["by_device"]) == 8, summ["by_device"]
print(f"mesh smoke OK: sharded backend matched single-device to 1e-5, "
      f"24 dynbatch frames exact over 8 chips, device_exec spans on "
      f"{len(dev_tracks)} device tracks ({dev_tracks[0]}..{dev_tracks[-1]}), "
      f"compile misses={summ['compiles']['miss']} (no per-frame churn)")
PY

run_step "Utilization smoke (nnstpu_mfu + busy-fraction series, device_idle spans, mfu.ladder plumbing + bank idempotence)" \
  env NNSTPU_MESH=dp:8 XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      JAX_PLATFORMS=cpu NNSTPU_OBS_DEVICE_IDLE_GAP_MS=10 \
  python - <<'PY'
# The device utilization observatory (ISSUE 11): on a CPU-mesh host a
# dynbatch pipeline must expose per-device nnstpu_mfu and
# nnstpu_device_busy_fraction series with roofline-classified
# device_exec span args; device starvation must render as device_idle
# spans in the Perfetto export; and the bench mfu.ladder leg must run
# its full 12-cell plumbing off-accel (every cell a typed skip) with an
# idempotent evidence-bank merge.
import os
import tempfile
import time

import jax
jax.config.update('jax_platforms', 'cpu')
import numpy as np

from nnstreamer_tpu import Pipeline
from nnstreamer_tpu.backends.jax_backend import JaxModel
from nnstreamer_tpu.buffer import Frame
from nnstreamer_tpu.elements.dynbatch import DynBatch, DynUnbatch
from nnstreamer_tpu.elements.filter import TensorFilter
from nnstreamer_tpu.elements.sink import TensorSink
from nnstreamer_tpu.elements.testsrc import DataSrc
from nnstreamer_tpu.graph.node import Node
from nnstreamer_tpu.obs import hooks, spans
from nnstreamer_tpu.obs import util as obs_util
from nnstreamer_tpu.obs.collector import attribute_trace
from nnstreamer_tpu.obs.device import DeviceTracer
from nnstreamer_tpu.obs.export import render_text
from nnstreamer_tpu.obs.metrics import MetricsRegistry

assert len(jax.devices()) == 8

# -- mesh dynbatch pipeline: per-device MFU + busy series ---------------
import jax.numpy as jnp
W = np.random.default_rng(0).standard_normal((64, 64)).astype(np.float32)
mdl = JaxModel(apply=lambda p, x: jnp.tanh(x @ W), input_spec=None)
reg = MetricsRegistry()
p = Pipeline(name="ci_util")
src = p.add(DataSrc(data=[np.ones(64, np.float32) for _ in range(24)],
                    name="s"))
p.link_chain(src, p.add(DynBatch(max_batch=8, name="db")),
             p.add(TensorFilter(framework="jax", model=mdl, name="f")),
             p.add(DynUnbatch(name="un")),
             p.add(TensorSink(name="out")))
dev = p.attach_tracer(DeviceTracer(registry=reg))
p.run(timeout=120)
deadline = time.time() + 30
while time.time() < deadline:
    s = dev.summary()
    if s["dispatches"] and s["completed"] == s["dispatches"]:
        break
    time.sleep(0.05)
summ = dev.summary()
assert len(summ["by_device"]) == 8, summ["by_device"]
for label, d in summ["by_device"].items():
    assert d["mfu"] is not None and d["mfu"] > 0, (label, d)
    assert 0.0 <= d["busy_fraction"] <= 1.0, (label, d)
text = render_text(reg)
mfu_series = [l for l in text.splitlines() if l.startswith("nnstpu_mfu{")]
busy_series = [l for l in text.splitlines()
               if l.startswith("nnstpu_device_busy_fraction{")]
assert len(mfu_series) >= 8, mfu_series
assert len(busy_series) == 8, busy_series
execs = [r for r in spans.snapshot()
         if r[0] == spans.PH_COMPLETE and r[4] == "device_exec"]
assert execs and all(
    r[9].get("flops") and r[9].get("roofline") in
    ("compute_bound", "bandwidth_bound") for r in execs), execs[-1][9]

# -- device_idle dead-time spans + attribution leg ----------------------
reg2 = MetricsRegistry()
p2 = Pipeline(name="ci_idle")
node = p2.add(Node(name="f"))
tr = DeviceTracer(registry=reg2, capacity=8)
p2._tracers.append(tr)
tr.start(p2)
trace_id = spans.new_trace_id()
frame = Frame.of(np.zeros(4, np.float32))
frame.meta[spans.META_KEY] = [trace_id, 1, 0, None]
for pause in (0.0, 0.05):  # 50 ms gap >> the 10 ms threshold
    time.sleep(pause)
    hooks.emit("device_dispatch", node, frame,
               (np.zeros(4, np.float32),), time.perf_counter_ns())
    deadline = time.time() + 10
    while tr.summary()["completed"] < 1 and time.time() < deadline:
        time.sleep(0.01)
deadline = time.time() + 10
while tr.summary()["completed"] < 2 and time.time() < deadline:
    time.sleep(0.01)
tr.stop()
doc = spans.chrome_trace(spans.snapshot())
idle_events = [e for e in doc["traceEvents"]
               if e.get("ph") == "X" and e["name"] == "device_idle"]
assert idle_events, "no device_idle span in the Perfetto export"
assert idle_events[0]["args"]["reason"] in (
    "host_dispatch", "queue_wait", "wire")
legs = attribute_trace(
    [r for r in spans.snapshot()
     if r[0] == spans.PH_COMPLETE and r[6] == trace_id])
assert legs.get("device_idle", 0) > 0, legs

# -- mfu.ladder plumbing + evidence-bank idempotence --------------------
import bench
with tempfile.TemporaryDirectory() as tmp:
    bench.TPU_CACHE_PATH = os.path.join(tmp, "cache.json")
    res = bench.measure_mfu_ladder(lambda label: None, on_accel=False)
    cells = res["cells"]
    assert len(cells) == 12, sorted(cells)
    assert all(c["skipped"]["reason"] in ("wire", "no_accel")
               for c in cells.values()), cells
    key = bench.ladder_cell_key(32, "int8", 8, "fast")
    cell = {"batch": 32, "dtype": "int8", "mesh": 8, "mfu": 0.12,
            "wire_regime": "fast", "measured_at": "ci"}
    b1 = bench.merge_ladder_bank({key: cell})
    b2 = bench.merge_ladder_bank({key: cell})
    assert b1 == b2 == bench.load_ladder_bank(), (b1, b2)
    res2 = bench.measure_mfu_ladder(lambda label: None, on_accel=False)
    assert res2["banked_cells"] == 1 and res2["bank"][key]["mfu"] == 0.12

print(f"utilization smoke OK: {len(mfu_series)} nnstpu_mfu series + "
      f"{len(busy_series)} busy-fraction series over 8 devices, "
      f"{len(execs)} roofline-classified device_exec spans, "
      f"{len(idle_events)} device_idle span(s) "
      f"(reason={idle_events[0]['args']['reason']}, device_idle leg "
      f"attributed), mfu.ladder 12/12 cells typed-skipped off-accel, "
      f"evidence bank idempotent")
PY

run_step "Cost-observatory smoke (stage-cost gauges, COST_MODEL.json idempotence, device-lane reconciliation, perfdiff self-compare)" \
  env JAX_PLATFORMS=cpu \
  python - <<'PY'
# The pipeline cost observatory (ISSUE 16): a CPU pipeline under the
# costmodel tracer must expose nnstpu_stage_cost_us{pipeline,node,leg}
# series and the cost_model stats provider; its device_exec leg must
# reconcile with the device lane's own accounting within 5%; the
# persisted COST_MODEL.json must be idempotent across two flushes AND
# across two whole runs; and a perfdiff self-compare must type every
# verdict flat with exit code 0.
import json
import os
import tempfile
import time

tmp = tempfile.mkdtemp(prefix="ci_costmodel_")
os.environ["NNSTPU_OBS_COSTMODEL_PATH"] = os.path.join(tmp, "COST_MODEL.json")

import jax
jax.config.update('jax_platforms', 'cpu')
import numpy as np

from nnstreamer_tpu import Pipeline
from nnstreamer_tpu.backends.jax_backend import JaxModel
from nnstreamer_tpu.elements.filter import TensorFilter
from nnstreamer_tpu.elements.queue import Queue
from nnstreamer_tpu.elements.sink import TensorSink
from nnstreamer_tpu.elements.testsrc import DataSrc
from nnstreamer_tpu.obs.costmodel import CostModelTracer, load_cost_model
from nnstreamer_tpu.obs.device import DeviceTracer
from nnstreamer_tpu.obs.export import stats_snapshot
from nnstreamer_tpu.obs.metrics import MetricsRegistry
from nnstreamer_tpu.spec import TensorSpec, TensorsSpec
from tools import perfdiff


def run_once():
    reg = MetricsRegistry()
    model = JaxModel(apply=lambda params, x: x * 2,
                     input_spec=TensorsSpec.of(
                         TensorSpec(dtype=np.float32, shape=(4,))))
    got = []
    p = Pipeline(name="cicost")
    src = p.add(DataSrc(data=[np.full(4, i, np.float32)
                              for i in range(8)], name="s"))
    filt = p.add(TensorFilter(framework="jax", model=model, name="f"))
    q = p.add(Queue(max_size_buffers=4, name="q"))
    p.link_chain(src, filt, q, p.add(TensorSink(callback=got.append,
                                                name="out")))
    dev = p.attach_tracer(DeviceTracer(registry=reg))
    cm = p.attach_tracer(CostModelTracer(registry=reg))
    p.run(timeout=120)
    deadline = time.time() + 30
    while time.time() < deadline and (dev.summary()["completed"] < 8
                                      or len(got) < 8):
        time.sleep(0.05)
    p.stop()
    return reg, dev, cm


reg, dev, cm = run_once()

# live series + stats provider
reg.collect()
labels = {k for k, _ in reg.get("nnstpu_stage_cost_us").children()}
assert ("cicost", "f", "dispatch") in labels, sorted(labels)
assert ("cicost", "f", "device_exec") in labels, sorted(labels)
assert ("cicost", "q", "queue_wait") in labels, sorted(labels)
assert "cicost" in stats_snapshot()["cost_model"]

# device_exec must reconcile with the device lane (same reaper feed)
stages = cm.stage_snapshots()
key = [k for k in stages if "|f|" in k][0]
leg = stages[key]["legs"]["device_exec"]
cm_us = leg["mean_us"] * leg["count"]
dev_us = dev.summary()["device_ns"] / 1e3
drift = abs(cm_us - dev_us) / max(dev_us, 1e-9)
assert drift < 0.05, (cm_us, dev_us, drift)

# flush idempotence within a run
d1, d2 = cm.flush(), cm.flush()
assert d1["stages"][key]["legs"] == d2["stages"][key]["legs"]

# idempotence across two WHOLE runs: the doc stays valid, history is
# per-run, the pooled aggregate only grows by the second run's samples
n1 = d2["stages"][key]["legs"]["device_exec"]["count"]
run_once()
doc = load_cost_model()
pooled = doc["stages"][key]["legs"]["device_exec"]
assert pooled["count"] > n1 and len(doc["stages"][key]["runs"]) == 2

# perfdiff self-compare: every verdict flat, exit 0, nothing regressed
rc = perfdiff.main(["--json"])
assert rc == 0
rep = perfdiff.report(perfdiff.diff_cost_models(doc, doc))
assert rep["verdict"] == "flat" and rep["regressed"] == 0, rep
assert rep["compared"] >= 3

print(f"cost-observatory smoke OK: {len(labels)} stage-cost series, "
      f"device_exec reconciled to {100 * drift:.2f}% of the device "
      f"lane, COST_MODEL.json idempotent ({pooled['count']} pooled "
      f"samples over 2 runs), perfdiff self-compare flat over "
      f"{rep['compared']} legs")
PY

run_step "Sentinel dry-run (sick→healthy flip triggers exactly one provenance-stamped ladder run)" \
  env JAX_PLATFORMS=cpu BENCH_MFU_LADDER_ON_CPU=1 \
  python - <<'PY'
# The benchmark sentinel (ISSUE 16): a canned sick→healthy probe
# sequence through the real flip detector must trigger EXACTLY one
# mfu.ladder run (forced-CPU, grid shrunk to one tiny cell), and the
# measured cell must land in the evidence bank carrying the sentinel
# provenance stamp — idempotently across a second dry-run.
import json
import os
import tempfile

tmp = tempfile.mkdtemp(prefix="ci_sentinel_")
os.environ["BENCH_TPU_CACHE_PATH"] = os.path.join(tmp, "cache.json")

from tools import sentinel

assert sentinel.main(["--dry-run", "--tiny-ladder"]) == 0

import bench

bank = bench.load_ladder_bank()
(cell,) = bank.values()
assert cell["provenance"] == {"source": "sentinel"}, cell
assert cell.get("mfu") is not None and cell["step_ms"] > 0

# a second recovery re-banks best-of: still one cell, still stamped
assert sentinel.main(["--dry-run", "--tiny-ladder"]) == 0
bank2 = bench.load_ladder_bank()
assert len(bank2) == 1
(cell2,) = bank2.values()
assert cell2["provenance"]["source"] == "sentinel"

print(f"sentinel dry-run OK: one trigger per flip, banked cell "
      f"{list(bank2)[0]} (mfu {cell2['mfu']}) stamped "
      f"provenance={cell2['provenance']}, bank idempotent")
PY

run_step "Fleet smoke (router + 3 workers: kill -9, SIGTERM drain, /healthz convergence)" \
  python - <<'PY'
import jax
jax.config.update('jax_platforms', 'cpu')
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np

from nnstreamer_tpu.elements.query import (
    QueryError, QuerySessionBrokenError, QueryUnavailableError,
    recv_tensors, send_tensors)

DECODE = "capacity=2,t_max=8,d_in=4,n_out=4,d_model=16,n_heads=2,n_layers=1"


def spawn(args):
    p = subprocess.Popen(
        [sys.executable, "-m", "nnstreamer_tpu.fleet"] + args
        + ["--platform", "cpu"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
    line = p.stdout.readline()  # the JSON ports line
    return p, json.loads(line)


procs = []
try:
    workers = []
    for i in range(3):
        p, info = spawn(["worker", "--name", f"w{i}", "--port", "0",
                         "--health-port", "0", "--model", "x2",
                         "--decode", DECODE, "--decode-port", "0",
                         "--drain-timeout", "5"])
        procs.append(p)
        workers.append(info)
    qspec = ",".join(f"127.0.0.1:{w['port']}/{w['health_port']}"
                     for w in workers)
    dspec = ",".join(f"127.0.0.1:{w['decode_port']}/{w['health_port']}"
                     for w in workers)
    qr_p, qr = spawn(["router", "--name", "qrouter", "--port", "0",
                      "--health-port", "0", "--workers", qspec])
    procs.append(qr_p)
    dr_p, dr = spawn(["router", "--name", "drouter", "--port", "0",
                      "--health-port", "0", "--stateful",
                      "--workers", dspec])
    procs.append(dr_p)

    def q_request(val):
        s = socket.create_connection(("127.0.0.1", qr["port"]), timeout=20)
        s.settimeout(20)
        try:
            send_tensors(s, (np.full(4, val, np.float32),), 0)
            outs, _ = recv_tensors(s)
            return float(np.asarray(outs[0])[0])
        finally:
            s.close()

    stateless = {"n": 0, "errors": []}
    stop = threading.Event()

    def q_client():
        i = 0
        while not stop.is_set():
            i += 1
            try:
                assert q_request(float(i)) == 2.0 * i
                stateless["n"] += 1
            except Exception as exc:  # noqa: BLE001
                stateless["errors"].append(repr(exc))
            time.sleep(0.01)

    decode = {"delivered": 0, "typed": 0, "untyped": [], "rebuilt": 0}

    def d_client():
        s = None
        while not stop.is_set():
            try:
                if s is None:
                    s = socket.create_connection(
                        ("127.0.0.1", dr["port"]), timeout=20)
                    s.settimeout(20)
                send_tensors(s, (np.zeros(4, np.float32),), 0)
                outs, _ = recv_tensors(s)
                assert np.asarray(outs[0]).shape == (4,)
                decode["delivered"] += 1
            except (QuerySessionBrokenError, QueryUnavailableError,
                    QueryError):
                decode["typed"] += 1
                if s is not None:
                    s.close(); s = None
                decode["rebuilt"] += 1
            except (ConnectionError, OSError):
                decode["typed"] += 1  # torn socket right after the typed frame
                if s is not None:
                    s.close(); s = None
            except Exception as exc:  # noqa: BLE001
                decode["untyped"].append(repr(exc))
            time.sleep(0.02)
        if s is not None:
            s.close()

    ths = [threading.Thread(target=q_client) for _ in range(3)] \
        + [threading.Thread(target=d_client) for _ in range(2)]
    for t in ths:
        t.start()
    time.sleep(1.0)                       # traffic established
    # kill -9 a worker that is HOSTING a live decode session (so the
    # stateful fail-fast contract is actually exercised), SIGTERM-drain
    # one of the others
    with urllib.request.urlopen(
            f"http://127.0.0.1:{dr['health_port']}/stats.json",
            timeout=10) as r:
        by_worker = json.load(r)["fleet:drouter"]["sessions_by_worker"]
    victim = sorted(by_worker)[0]            # worker id == "host:port"
    vi = next(i for i, w in enumerate(workers)
              if victim.endswith(f":{w['decode_port']}"))
    di = next(i for i in range(3) if i != vi)
    os.kill(workers[vi]["pid"], signal.SIGKILL)   # crash mid-stream
    time.sleep(0.6)
    os.kill(workers[di]["pid"], signal.SIGTERM)   # drain mid-stream
    time.sleep(2.5)                       # ride through the churn
    stop.set()
    for t in ths:
        t.join(timeout=30)

    assert stateless["errors"] == [], \
        f"stateless errors surfaced: {stateless['errors'][:3]}"
    assert stateless["n"] >= 50, stateless
    assert decode["untyped"] == [], decode
    assert decode["typed"] >= 1, decode   # the kill was felt, typed only
    assert decode["delivered"] >= 10, decode

    # /healthz convergence: the survivor answers 200-json, the killed and
    # drained workers are down in the router's membership view
    si = next(i for i in range(3) if i not in (vi, di))
    with urllib.request.urlopen(
            f"http://127.0.0.1:{workers[si]['health_port']}/healthz",
            timeout=10) as r:
        doc = json.loads(r.read())
        assert r.status == 200 and doc["status"] == "ok", doc

    def converged():
        with urllib.request.urlopen(
                f"http://127.0.0.1:{qr['health_port']}/stats.json",
                timeout=10) as r:
            st = json.load(r)["fleet:qrouter"]
        states = {k: v["state"] for k, v in st["membership"]["workers"].items()}
        up = [k for k, v in states.items() if v == "up"]
        gone = [k for k, v in states.items()
                if v in ("down", "suspect", "unhealthy")]
        ok = len(up) == 1 and len(gone) == 2 \
            and st["offered"] == st["delivered"] + st["shed_total"]
        return ok, states, st

    deadline = time.time() + 20
    ok, states, st = converged()
    while time.time() < deadline and not ok:
        time.sleep(0.2)
        ok, states, st = converged()
    assert ok, (states, st["offered"], st["delivered"], st["shed_total"])
    print(f"fleet smoke OK: {stateless['n']} stateless requests with zero "
          f"errors through a kill -9 + SIGTERM drain; decode sessions "
          f"broke typed only ({decode['typed']} typed, "
          f"{decode['delivered']} steps delivered); router ledger "
          f"{st['offered']}=={st['delivered']}+{st['shed_total']}; "
          f"membership converged {states}")
finally:
    for p in procs:
        try:
            p.kill()
        except OSError:
            pass
PY

run_step "Migration smoke (SIGTERM-drain a session-hosting worker: zero [SESSION], token-identical)" \
  python - <<'PY'
# ISSUE 12 acceptance, subprocess edition: a stateful fleet (2 decode
# workers + repo + migrating router), a live decode session mid-
# generation, SIGTERM the session-hosting worker — the router's
# migration monitor moves the session to the survivor, the client sees
# ZERO errors, the transcript is token-identical to an unmigrated
# control run, the session ledger stays exact, and
# nnstpu_session_migrations_total{result="ok"} >= 1 on the router's
# /metrics.  Stateless traffic rides its own router through the same
# churn with an exact offered == delivered + shed ledger.
import jax
jax.config.update('jax_platforms', 'cpu')
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np

from nnstreamer_tpu.elements.query import recv_tensors, send_tensors
from nnstreamer_tpu.serving import ContinuousBatcher

DECODE = "capacity=2,t_max=8,d_in=4,n_out=4,d_model=16,n_heads=2,n_layers=1"
ENGINE = dict(capacity=2, t_max=8, d_in=4, n_out=4, d_model=16, n_heads=2,
              n_layers=1)


def spawn(args):
    p = subprocess.Popen(
        [sys.executable, "-m", "nnstreamer_tpu.fleet"] + args
        + ["--platform", "cpu"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
    line = p.stdout.readline()
    return p, json.loads(line)


procs = []
try:
    repo_p, repo = spawn(["repo", "--port", "0"])
    procs.append(repo_p)
    workers = []
    for i in range(2):
        p, info = spawn(["worker", "--name", f"mw{i}", "--port", "0",
                         "--health-port", "0", "--model", "x2",
                         "--decode", DECODE, "--decode-port", "0",
                         "--drain-timeout", "8"])
        procs.append(p)
        workers.append(info)
    qspec = ",".join(f"127.0.0.1:{w['port']}/{w['health_port']}"
                     for w in workers)
    dspec = ",".join(f"127.0.0.1:{w['decode_port']}/{w['health_port']}"
                     for w in workers)
    qr_p, qr = spawn(["router", "--name", "mig-q", "--port", "0",
                      "--health-port", "0", "--workers", qspec])
    procs.append(qr_p)
    dr_p, dr = spawn(["router", "--name", "mig-d", "--port", "0",
                      "--health-port", "0", "--stateful",
                      "--repo", f"127.0.0.1:{repo['port']}",
                      "--workers", dspec])
    procs.append(dr_p)

    prompt = np.random.RandomState(0).rand(3, 4).astype(np.float32)
    steps = [np.random.RandomState(i + 10).rand(4).astype(np.float32)
             for i in range(24)]

    # control transcript: one unmigrated in-process engine, same params
    with ContinuousBatcher(**ENGINE) as ctl_eng:
        cs = ctl_eng.open_session()
        cs.prefill(prompt)
        control = [cs.get(timeout=15)]
        for s in steps:
            cs.feed(s)
            control.append(cs.get(timeout=15))
        cs.close()

    # stateless traffic through the same churn window (exact ledger)
    stateless = {"n": 0, "errors": []}
    stop = threading.Event()

    def q_client():
        i = 0
        while not stop.is_set():
            i += 1
            try:
                s = socket.create_connection(("127.0.0.1", qr["port"]),
                                             timeout=20)
                s.settimeout(20)
                send_tensors(s, (np.full(4, float(i), np.float32),), 0)
                outs, _ = recv_tensors(s)
                assert float(np.asarray(outs[0])[0]) == 2.0 * i
                stateless["n"] += 1
                s.close()
            except Exception as exc:  # noqa: BLE001
                stateless["errors"].append(repr(exc))
            time.sleep(0.01)

    qt = threading.Thread(target=q_client)
    qt.start()

    # the migrating session: prefill + paced steps spanning the drain
    sock = socket.create_connection(("127.0.0.1", dr["port"]), timeout=20)
    sock.settimeout(20)

    def rt(arr):
        send_tensors(sock, (arr,), 0)
        outs, _ = recv_tensors(sock)
        return np.asarray(outs[0])

    out = [rt(prompt)]
    for s in steps[:6]:
        out.append(rt(s))

    with urllib.request.urlopen(
            f"http://127.0.0.1:{dr['health_port']}/stats.json",
            timeout=10) as r:
        by_worker = json.load(r)["fleet:mig-d"]["sessions_by_worker"]
    victim_addr = next(iter(by_worker))
    vi = next(i for i, w in enumerate(workers)
              if victim_addr.endswith(f":{w['decode_port']}"))
    os.kill(workers[vi]["pid"], signal.SIGTERM)  # drain mid-generation
    for s in steps[6:]:                          # stream THROUGH the drain
        out.append(rt(s))
        time.sleep(0.05)
    stop.set()
    qt.join(timeout=30)
    sock.close()

    assert len(out) == len(control)
    for i, (x, y) in enumerate(zip(control, out)):
        np.testing.assert_array_equal(x, y, err_msg=f"token {i}")
    assert stateless["errors"] == [], stateless["errors"][:3]
    assert stateless["n"] >= 20, stateless

    # the drained worker exits 0 (its decode drain completed clean —
    # the session was migrated off, not force-broken)
    assert procs[1 + vi].wait(timeout=30) == 0

    with urllib.request.urlopen(
            f"http://127.0.0.1:{dr['health_port']}/stats.json",
            timeout=10) as r:
        st = json.load(r)["fleet:mig-d"]
    assert st["sessions_migrated"] >= 1, st
    assert st["sessions_broken"] == 0, st
    assert st["session_ledger_exact"], st
    with urllib.request.urlopen(
            f"http://127.0.0.1:{qr['health_port']}/stats.json",
            timeout=10) as r:
        qst = json.load(r)["fleet:mig-q"]
    assert qst["offered"] == qst["delivered"] + qst["shed_total"], qst
    with urllib.request.urlopen(
            f"http://127.0.0.1:{dr['health_port']}/metrics",
            timeout=10) as r:
        metrics = r.read().decode()
    ok_line = next(
        (ln for ln in metrics.splitlines()
         if ln.startswith("nnstpu_session_migrations_total")
         and 'result="ok"' in ln), "")
    assert ok_line and float(ok_line.rsplit(" ", 1)[1]) >= 1, ok_line
    print(f"migration smoke OK: SIGTERM drain mid-generation migrated "
          f"the session ({ok_line.rsplit(' ', 1)[1]} ok handoffs), "
          f"{len(out)} outputs token-identical to the unmigrated "
          f"control, zero [SESSION] errors, session ledger exact, "
          f"{stateless['n']} stateless requests zero-error with "
          f"{qst['offered']}=={qst['delivered']}+{qst['shed_total']}")
finally:
    for p in procs:
        try:
            p.kill()
        except OSError:
            pass
PY

run_step "Autoscale smoke (seeded spike: scale up, kill -9 + respawn, rolling drain with migrated session)" \
  python - <<'PY'
# ISSUE 15 acceptance, subprocess edition: one `fleet autoscale` process
# (query router + stateful decode router + self-hosted repo + supervisor
# + autoscaler) spawning worker subprocesses on ephemeral ports.  A
# spike scales the fleet 1 -> 3 within the window; kill -9 of a
# scaled-up worker mid-traffic is respawned by the supervisor
# (warming-gated, fresh incarnation); the post-spike down-slope drains
# back to 1 via rolling SIGTERM with the live decode sessions migrated
# (zero [SESSION]); nnstpu_autoscale_events_total{action} and the exact
# spawn + router ledgers are asserted.
import jax
jax.config.update('jax_platforms', 'cpu')
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np

from nnstreamer_tpu.elements.query import recv_tensors, send_tensors

DECODE = "capacity=4,t_max=8,d_in=4,n_out=4,d_model=16,n_heads=2,n_layers=1"

proc = subprocess.Popen(
    [sys.executable, "-m", "nnstreamer_tpu.fleet", "autoscale",
     "--port", "0", "--health-port", "0", "--model", "x2",
     "--min-workers", "1", "--max-workers", "3", "--worker-rps", "40",
     "--warmup-spec", "float32:4", "--decode", DECODE,
     "--platform", "cpu"],
    stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
try:
    info = json.loads(proc.stdout.readline())
    assert info["role"] == "autoscale" and info["repo_port"], info
    health = info["health_port"]

    def stats():
        with urllib.request.urlopen(
                f"http://127.0.0.1:{health}/stats.json", timeout=10) as r:
            return json.load(r)

    def asc():
        return stats()["autoscale:autoscale"]

    def wait_ready(n, timeout, cmp=lambda a, b: a >= b):
        deadline = time.time() + timeout
        while time.time() < deadline:
            try:
                if cmp(asc()["ready"], n):
                    return True
            except (KeyError, OSError):
                pass
            time.sleep(0.3)
        return cmp(asc()["ready"], n)

    assert wait_ready(1, 120), asc()   # the floor worker joined (warmed)

    errors, delivered = [], [0]
    stop = threading.Event()
    spike = threading.Event()

    def q_client(gap_s, gate):
        i = 0
        while not stop.is_set():
            if gate is not None and not gate.is_set():
                time.sleep(0.05)
                continue
            i += 1
            try:
                s = socket.create_connection(
                    ("127.0.0.1", info["port"]), timeout=20)
                s.settimeout(20)
                send_tensors(s, (np.full(4, float(i), np.float32),), 0)
                outs, _ = recv_tensors(s)
                assert float(np.asarray(outs[0])[0]) == 2.0 * i
                delivered[0] += 1
                s.close()
            except Exception as exc:  # noqa: BLE001
                errors.append(repr(exc))
            time.sleep(gap_s)

    ths = [threading.Thread(target=q_client, args=(0.1, None))
           for _ in range(2)]
    ths += [threading.Thread(target=q_client, args=(0.004, spike))
            for _ in range(8)]
    for t in ths:
        t.start()
    time.sleep(2.0)
    assert asc()["ready"] == 1, asc()  # trickle fits the floor

    spike.set()                        # the seeded spike hits
    assert wait_ready(3, 120), asc()   # scaled up within the window
    print(f"scale-up OK: fleet at 3 within window "
          f"(decision: {asc()['last_decision']})")

    # live decode sessions across the scaled-up fleet (round-robin
    # pins them on distinct workers, so the down-slope MUST migrate)
    sessions = []
    for _ in range(2):
        s = socket.create_connection(
            ("127.0.0.1", info["decode_port"]), timeout=30)
        s.settimeout(30)
        send_tensors(s, (np.full((5, 4), 0.1, np.float32),), 0)
        recv_tensors(s)
        sessions.append(s)

    # kill -9 a scaled-up worker mid-traffic: the supervisor must
    # respawn it (fresh incarnation, warming-gated join).  Pick one
    # that is NOT hosting a session (the kill tests respawn, not the
    # stateful fail-fast contract).
    st = stats()
    hosts = set(st.get("fleet:autoscale-decode", {})
                .get("sessions_by_worker", {}))
    workers = asc()["supervisor"]["workers"]
    victim = next(w for w, snap in sorted(workers.items())
                  if snap["state"] == "up" and snap["pid"]
                  and w not in hosts)
    os.kill(workers[victim]["pid"], signal.SIGKILL)
    deadline = time.time() + 120
    while time.time() < deadline:
        snap = asc()
        if snap["supervisor"]["workers"].get(victim, {}).get(
                "restarts", 0) >= 1 and snap["ready"] >= 3:
            break
        time.sleep(0.3)
    snap = asc()
    assert snap["supervisor"]["workers"][victim]["restarts"] >= 1, snap
    assert snap["ready"] == 3, snap
    print(f"respawn OK: {victim} killed -9 and supervised back to ready")

    spike.clear()                      # the down-slope
    assert wait_ready(1, 120, cmp=lambda a, b: a <= b), asc()
    # the sessions survived the rolling migrate-first drain: they still
    # step, zero [SESSION]
    for s in sessions:
        for _ in range(3):
            send_tensors(s, (np.zeros(4, np.float32),), 0)
            outs, _ = recv_tensors(s)
            assert np.asarray(outs[0]).shape == (4,)
    for s in sessions:
        s.close()
    stop.set()
    for t in ths:
        t.join(timeout=60)

    st = stats()
    snap = st["autoscale:autoscale"]
    drt = st["fleet:autoscale-decode"]
    qrt = st["fleet:autoscale"]
    assert errors == [], f"stateless errors: {errors[:3]}"
    assert drt["sessions_broken"] == 0, drt
    assert drt["sessions_migrated"] >= 1, drt
    # ledgers: the autoscaler's own (spawns == joined+failed+quarantined)
    # and the router's (offered == delivered + shed), both exact
    assert snap["ledger_exact"], snap
    assert snap["spawns"] == snap["joined"] + snap["failed"] \
        + snap["quarantined"] + snap["pending"], snap
    assert snap["fleet_size_min"] == 1 and snap["fleet_size_max"] == 3
    assert qrt["offered"] == qrt["delivered"] + qrt["shed_total"], qrt
    assert qrt["offered"] >= delivered[0]

    # the metric family: every transition counted by action
    with urllib.request.urlopen(
            f"http://127.0.0.1:{health}/metrics", timeout=10) as r:
        expo = r.read().decode()
    counts = {}
    for line in expo.splitlines():
        if line.startswith("nnstpu_autoscale_events_total{"):
            action = line.split('action="')[1].split('"')[0]
            counts[action] = counts.get(action, 0) + int(float(
                line.rsplit(" ", 1)[1]))
    assert counts.get("spawn", 0) >= 3, counts      # floor + 2 scale-ups
    assert counts.get("join", 0) >= 4, counts       # incl. the respawn
    assert counts.get("respawn", 0) >= 1, counts
    assert counts.get("drain", 0) >= 2, counts
    print(f"autoscale smoke OK: 1->3->1 with kill -9 respawn; "
          f"{delivered[0]} stateless requests zero-error; "
          f"{drt['sessions_migrated']} session(s) migrated, 0 broken; "
          f"spawn ledger {snap['spawns']}=={snap['joined']}+"
          f"{snap['failed']}+{snap['quarantined']}; events {counts}")
finally:
    try:
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=30)
    except (OSError, subprocess.TimeoutExpired):
        proc.kill()
PY

run_step "Cold-start smoke (warm a pipeline, restart the process, zero compile misses)" \
  python - <<'PY'
# Compile-ahead acceptance gate: a warmed-then-restarted pipeline must
# serve its first frame with nnstpu_compile_total{result="miss"} == 0 —
# every executable reconstructed from the persistent cache (result in
# {hit, persist_hit} only) — and warmup-phase compile spans must land on
# the "warmup" Perfetto track, never inside the first frame's trace.
import json
import shutil
import subprocess
import sys
import tempfile

DRIVER = r'''
import json, os, time
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from nnstreamer_tpu import Pipeline
from nnstreamer_tpu.backends.jax_backend import JaxModel
from nnstreamer_tpu.elements.dynbatch import DynBatch, DynUnbatch
from nnstreamer_tpu.elements.filter import TensorFilter
from nnstreamer_tpu.elements.sink import TensorSink
from nnstreamer_tpu.elements.testsrc import DataSrc
from nnstreamer_tpu.obs import spans
from nnstreamer_tpu.obs.metrics import REGISTRY
from nnstreamer_tpu.spec import TensorSpec, TensorsSpec

D = 64
W = np.random.default_rng(0).standard_normal((D, D)).astype(np.float32)
model = JaxModel(apply=lambda p, x: jax.numpy.tanh(x @ W),
                 input_spec=TensorsSpec.of(
                     TensorSpec(dtype=np.float32, shape=(None, D))))
state = {"first": None}

def cb(frame):
    if state["first"] is None:
        np.asarray(frame.tensors[0])
        state["first"] = time.perf_counter()

p = Pipeline(name="ci_coldstart")
src = p.add(DataSrc(data=[np.ones(D, np.float32) for _ in range(4)]))
p.link_chain(src, p.add(DynBatch(max_batch=4)),
             p.add(TensorFilter(framework="jax", model=model)),
             p.add(DynUnbatch()), p.add(TensorSink(callback=cb)))
p.run(timeout=120)
assert state["first"] is not None, "no frame served"

c = REGISTRY.get("nnstpu_compile_total")
compiles = {k[0]: int(v.value) for k, v in dict(c.children()).items()}

# span attribution: every compile span sits on the "warmup" track
doc = spans.chrome_trace(spans.snapshot(), process_name="ci_coldstart")
comp = [e for e in doc["traceEvents"]
        if e.get("ph") == "X" and e["name"] == "compile"]
rows = {e["tid"]: e["args"]["name"] for e in doc["traceEvents"]
        if e.get("ph") == "M" and e["name"] == "thread_name"}
warm_rows = [tid for tid, name in rows.items() if name == "warmup"]
bad = [e for e in comp if e["tid"] not in warm_rows]
warmed = [e for e in doc["traceEvents"]
          if e.get("ph") == "X" and str(e["name"]).startswith("warm")]
print(json.dumps({"compiles": compiles, "compile_spans": len(comp),
                  "off_track": len(bad), "warmup_spans": len(warmed)}))
'''

cache = tempfile.mkdtemp(prefix="ci_coldstart_")
try:
    env = {"NNSTPU_COMPILE_CACHE_DIR": cache, "NNSTPU_COMPILE_WARMUP": "1",
           "NNSTPU_TRACERS": "spans", "JAX_PLATFORMS": "cpu",
           "PATH": "/usr/bin:/bin:/usr/local/bin"}
    import os

    env = dict(os.environ, **env)
    runs = {}
    for label in ("cold", "warm"):
        proc = subprocess.run([sys.executable, "-c", DRIVER], env=env,
                              capture_output=True, text=True, timeout=300)
        assert proc.returncode == 0, (label, proc.stderr[-800:])
        runs[label] = json.loads(proc.stdout.strip().splitlines()[-1])
    cold, warm = runs["cold"], runs["warm"]
    assert cold["compiles"].get("miss", 0) > 0, cold  # cold run really compiled
    assert warm["compiles"].get("miss", 0) == 0, \
        f"warmed restart still compiling: {warm['compiles']}"
    assert warm["compiles"].get("persist_hit", 0) > 0, warm
    for label, run in runs.items():
        assert run["compile_spans"] > 0 and run["off_track"] == 0, (label, run)
        assert run["warmup_spans"] > 0, (label, run)
    print(f"cold-start smoke OK: cold={cold['compiles']} -> "
          f"warm={warm['compiles']} (zero misses after restart); "
          f"all {warm['compile_spans']} compile spans on the warmup track")
finally:
    shutil.rmtree(cache, ignore_errors=True)
PY

run_step "Segment smoke (whole-segment compilation: one device_exec per dispatch, host-dispatch dead time within budget, zero compile misses after warm restart)" \
  python - <<'PY'
# Whole-segment acceptance gate (graph/segments.py): the SSD pipeline
# with the tflite-ssd decoder folded into the filter's program must
# (a) run exactly one device_exec span per frame — the whole
#     converter→model→decode region is ONE device program;
# (b) cut device_idle{reason=host_dispatch} dead time to ≤10% of the
#     unfused run's (the fold removes the 1917-anchor host decode from
#     between device programs; only the overlay tail remains);
# (c) serve a warm process restart with zero compile misses — the fused
#     executable persists under its composite (StableHLO sha + segment
#     label) cache key like any other program.
import json
import os
import shutil
import subprocess
import sys
import tempfile

DRIVER = r'''
import json, os, tempfile
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from nnstreamer_tpu import Pipeline, make
from nnstreamer_tpu.elements.filter import TensorFilter
from nnstreamer_tpu.elements.sink import TensorSink
from nnstreamer_tpu.elements.testsrc import DataSrc
from nnstreamer_tpu.models import ssd_mobilenet
from nnstreamer_tpu.obs import spans
from nnstreamer_tpu.obs.metrics import REGISTRY

N = 6
rng = np.random.default_rng(0)
frames = [rng.integers(0, 256, (300, 300, 3)).astype(np.uint8)
          for _ in range(N)]
model = ssd_mobilenet.build(num_labels=91, image_size=300)
priors_path = ssd_mobilenet.write_priors_file(
    os.path.join(tempfile.mkdtemp(prefix="ci_segment_priors_"),
                 "priors.txt"))
got = []
p = Pipeline(name="ci_segment")
src = p.add(DataSrc(data=frames))
conv = p.add(make("tensor_converter"))
norm = p.add(make("tensor_transform", mode="arithmetic",
                  option="typecast:float32,add:-127.5,div:127.5"))
filt = p.add(TensorFilter(framework="jax", model=model))
dec = p.add(make("tensor_decoder", mode="bounding_boxes",
                 option1="tflite-ssd", option3=priors_path,
                 option4="300:300", option5="300:300"))
sink = p.add(TensorSink(callback=got.append))
p.link_chain(src, conv, norm, filt, dec, sink)
p.start()
label = filt.backend.segment_label  # sampled while PLAYING
p.wait(300)
p.stop()
assert len(got) == N, f"delivered {len(got)}/{N} frames"

rows = spans.snapshot()
execs = [r for r in rows if r[0] == spans.PH_COMPLETE
         and r[4] == "device_exec"]
idle = [r for r in rows if r[0] == spans.PH_COMPLETE
        and r[4] == "device_idle"
        and r[9].get("reason") == "host_dispatch"]
c = REGISTRY.get("nnstpu_compile_total")
compiles = ({k[0]: int(v.value) for k, v in dict(c.children()).items()}
            if c else {})
print(json.dumps({
    "frames": len(got), "execs": len(execs), "label": label,
    "host_us_per_frame": sum(r[2] for r in idle) / 1e3 / N,
    "compiles": compiles,
}))
'''

base = dict(os.environ,
            JAX_PLATFORMS="cpu",
            NNSTPU_TRACERS="device",
            NNSTPU_OBS_DEVICE_IDLE_GAP_MS="0.05")

def child(label, **env):
    proc = subprocess.run([sys.executable, "-c", DRIVER],
                          env=dict(base, **env),
                          capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, (label, proc.stderr[-800:])
    return json.loads(proc.stdout.strip().splitlines()[-1])

cache = tempfile.mkdtemp(prefix="ci_segment_")
try:
    unf = child("unfused", NNSTPU_SEGMENT_ENABLED="0")
    assert unf["label"] == "", unf
    seg_env = {"NNSTPU_SEGMENT_ENABLED": "1",
               "NNSTPU_COMPILE_CACHE_DIR": cache,
               "NNSTPU_COMPILE_WARMUP": "1"}
    cold = child("segment-cold", **seg_env)
    assert cold["label"], "segment did not fold (empty segment label)"
    # (a) one device program per segment dispatch
    assert cold["execs"] == cold["frames"], cold
    # (b) the fold removes the host decode from between device programs
    budget = 0.10 * unf["host_us_per_frame"]
    assert cold["host_us_per_frame"] <= budget, \
        (f"fused host-dispatch {cold['host_us_per_frame']:.0f} us/frame "
         f"> 10% of unfused {unf['host_us_per_frame']:.0f}")
    assert cold["compiles"].get("miss", 0) > 0, cold  # really compiled
    # (c) warm restart: the fused executable reconstructs, never recompiles
    warm = child("segment-warm", **seg_env)
    assert warm["label"] == cold["label"], (warm, cold)
    assert warm["compiles"].get("miss", 0) == 0, \
        f"warm restart still compiling: {warm['compiles']}"
    assert warm["compiles"].get("persist_hit", 0) > 0, warm
    print(f"segment smoke OK: label={cold['label']!r}, "
          f"{cold['execs']}/{cold['frames']} device_exec, host-dispatch "
          f"{unf['host_us_per_frame']:.0f} -> {cold['host_us_per_frame']:.0f} "
          f"us/frame, warm restart compiles={warm['compiles']}")
finally:
    shutil.rmtree(cache, ignore_errors=True)
PY

run_step "Partition smoke (planner-pinned split of the SSD cascade across a subprocess fragment worker: merged trace hop arrows, exact ledger through seeded drops, regime flip = 1 repartition)" \
  python - <<'PY'
import jax
jax.config.update('jax_platforms', 'cpu')
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

import numpy as np

from nnstreamer_tpu import parse_launch
from nnstreamer_tpu.graph.parse import split_launch
from nnstreamer_tpu.obs import costmodel as obs_costmodel
from nnstreamer_tpu.obs import spans
from nnstreamer_tpu.obs import util as obs_util
from nnstreamer_tpu.obs.collector import TraceCollector
from nnstreamer_tpu.obs.spans import SpanTracer
from nnstreamer_tpu.partition import (
    PartitionDeployment, RepartitionMonitor, plan_partition)

tmp = tempfile.mkdtemp(prefix="partition_smoke_")
model_py = os.path.join(tmp, "cascade_model.py")
with open(model_py, "w") as f:
    f.write(
        "from nnstreamer_tpu.models import cascade\n"
        "def get_model():\n"
        "    return cascade.build_detect_classify(\n"
        "        num_labels=91, det_size=300, k=4, crop_size=96,\n"
        "        num_classes=101, width_mult=0.5, seed=0)\n")

DESC = (
    "videotestsrc num-buffers=8 pattern=smpte width=300 height=300 ! "
    "tensor_converter name=conv ! "
    "tensor_transform mode=arithmetic option=typecast:float32,add:-127.5,"
    "div:127.5 name=norm ! "
    f"tensor_filter framework=jax model={model_py} name=cascade ! "
    "tensor_sink name=out collect=true")

# -- phase 0: golden reference (unsplit, in-process) ------------------------
ref = parse_launch(DESC)
ref.start(); ref.wait(300); ref.stop()
want = [[np.asarray(t) for t in fr.tensors] for fr in ref.nodes["out"].frames]
assert len(want) == 8, f"golden run produced {len(want)} frames"

# -- phase 1: the planner picks the cut from measured inputs ----------------
sk = obs_costmodel.stage_key
COST_MODEL = {"schema": 1, "stages": {
    # copy_bytes = what crosses the wire INTO that stage: raw video
    # (RGBA-padded, 360 KB) into conv, packed uint8 (270 KB) into norm,
    # normalized float32 (1.08 MB) into cascade — cut=2 is the cheapest
    # crossing, and the 10x server roofline makes it beat all-local
    sk("smoke", "conv"): {"legs": {"device_exec": {
        "count": 5, "mean_us": 100.0, "m2": 400.0}}, "runs": [],
        "copy_bytes_per_frame": 360_000.0},
    sk("smoke", "norm"): {"legs": {"device_exec": {
        "count": 5, "mean_us": 2000.0, "m2": 400.0}}, "runs": [],
        "copy_bytes_per_frame": 270_000.0},
    sk("smoke", "cascade"): {"legs": {"device_exec": {
        "count": 5, "mean_us": 50_000.0, "m2": 400.0}}, "runs": [],
        "flops_per_frame": 1e9, "copy_bytes_per_frame": 1_080_000.0},
}}
PEAKS = {"client": {"tflops": 0.1}, "server": {"tflops": 1.0}}
FAST = {"put_150k_ms": 0.5, "dispatch_ms": 0.2}

plan = plan_partition(DESC, pipeline="smoke", addr="127.0.0.1:0",
                      edge="edge0", cost_model=COST_MODEL,
                      wire_health=FAST, peaks=PEAKS)
assert plan.cut == 2, f"planner chose {plan.cut}: {[ (s.cut, s.total_us) for s in plan.scores ]}"
p2 = plan_partition(DESC, pipeline="smoke", addr="127.0.0.1:0",
                    edge="edge0", cost_model=COST_MODEL,
                    wire_health=FAST, peaks=PEAKS)
assert p2 == plan and p2.fingerprint == plan.fingerprint, "plan not reproducible"
print(f"planner: cut={plan.cut} fingerprint={plan.fingerprint} "
      f"scores={[(s.cut, s.total_us) for s in plan.scores]}")

# -- phase 2: subprocess server fragment, chaos on the split edge -----------
_, server_desc = split_launch(DESC, plan.cut)
env = dict(os.environ)
env["JAX_PLATFORMS"] = "cpu"
env["NNSTPU_FAULTS"] = "seed=7;socket_drop@server:every=3,count=2"
proc = subprocess.Popen(
    [sys.executable, "-m", "nnstreamer_tpu.fleet", "worker",
     "--name", "fragw", "--port", "0", "--health-port", "0",
     "--framework", "fragment", "--model", server_desc,
     "--spans", "--platform", "cpu"],
    stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True, env=env)
try:
    info = json.loads(proc.stdout.readline())
    assert info["role"] == "worker"

    client_desc, _ = split_launch(DESC, plan.cut, client_props={
        "name": "qc_edge0", "host": "127.0.0.1", "port": str(info["port"]),
        "caps": "true", "require_caps": "true", "edge": "edge0",
        "retries": "2", "retry_backoff_ms": "5", "request_timeout": "300",
    })
    spans.enable(8192)
    pipe = parse_launch(client_desc)
    pipe.attach_tracer(SpanTracer())
    pipe.start(); pipe.wait(300); pipe.stop()
    got = [[np.asarray(t) for t in fr.tensors]
           for fr in pipe.nodes["out"].frames]
    assert len(got) == 8, f"split run produced {len(got)} frames"
    for i, (w, g) in enumerate(zip(want, got)):
        assert len(w) == len(g)
        for wt, gt in zip(w, g):
            np.testing.assert_array_equal(wt, gt, err_msg=f"frame {i}")
    qc = pipe.nodes["qc_edge0"]
    assert qc._caps_wire is True, "split edge did not negotiate caps"
    assert qc.retries_total == 2, (
        f"chaos ledger: expected exactly 2 retried drops, saw "
        f"{qc.retries_total}")
    print(f"split run exact through chaos: 8/8 frames, "
          f"retries={qc.retries_total}, caps_wire={qc._caps_wire}")

    # -- merged Perfetto trace: client fragment -> hop -> server fragment
    tc = TraceCollector()
    tc.add_local("client")
    tc.add_http("fragw", info["trace_addr"])
    chrome = tc.chrome_trace()
    evs = chrome["traceEvents"]
    pids = {}
    for e in evs:
        if e.get("ph") == "X":
            pids.setdefault(e["name"], set()).add(e["pid"])
    rtt_pids = pids.get("nnsq_rtt", set())
    serve_pids = pids.get("nnsq_serve", set())
    assert rtt_pids and serve_pids and rtt_pids.isdisjoint(serve_pids), (
        f"client/server spans must sit on different pids: "
        f"rtt={rtt_pids} serve={serve_pids}")
    hop_s = [e for e in evs if e.get("name") == "nnsq_hop"
             and e["ph"] == "s"]
    hop_f = [e for e in evs if e.get("name") == "nnsq_hop"
             and e["ph"] == "f"]
    assert len(hop_s) >= 8 and len(hop_f) == len(hop_s), (
        f"expected >=8 hop arrows, got s={len(hop_s)} f={len(hop_f)}")
    by_id = {e["id"]: e for e in hop_s}
    for f_ev in hop_f:
        s_ev = by_id[f_ev["id"]]
        assert s_ev["pid"] != f_ev["pid"], "hop arrow must cross pids"
        assert s_ev["args"]["edge"] == "edge0"
    assert all(e["pid"] in rtt_pids for e in hop_s)
    assert all(e["pid"] in serve_pids for e in hop_f)
    trace_path = os.path.join(tmp, "partition_smoke.trace.json")
    with open(trace_path, "w") as f:
        json.dump(chrome, f)
    print(f"merged trace: {len(evs)} events, {len(hop_s)} client->server "
          f"hop arrows ({trace_path})")
finally:
    proc.send_signal(signal.SIGTERM)
    try:
        proc.wait(timeout=30)
    except subprocess.TimeoutExpired:
        proc.kill()
spans.disable()

# -- phase 3: forced wire-regime flip -> exactly one repartition ------------
cm_path = os.path.join(tmp, "COST_MODEL.json")
CM2 = {"schema": 1, "stages": {
    sk("rp", "conv"): {"legs": {"device_exec": {
        "count": 5, "mean_us": 100.0, "m2": 400.0}}, "runs": [],
        "copy_bytes_per_frame": 301_056.0},
    sk("rp", "scale"): {"legs": {"device_exec": {
        "count": 5, "mean_us": 4000.0, "m2": 400.0}}, "runs": [],
        "flops_per_frame": 1e9, "copy_bytes_per_frame": 150_528.0},
    sk("rp", "bias"): {"legs": {"device_exec": {
        "count": 5, "mean_us": 3000.0, "m2": 400.0}}, "runs": [],
        "flops_per_frame": 1e9, "copy_bytes_per_frame": 150_528.0},
}}
with open(cm_path, "w") as f:
    json.dump(CM2, f)
os.environ["NNSTPU_OBS_COSTMODEL_PATH"] = cm_path
RP_DESC = ("videotestsrc num-buffers=4 pattern=smpte width=4 height=4 ! "
           "tensor_converter name=conv ! "
           "tensor_transform mode=arithmetic option=mul:2.0 name=scale ! "
           "tensor_transform mode=arithmetic option=add:1.0 name=bias ! "
           "tensor_sink name=out")
rp_plan = plan_partition(RP_DESC, pipeline="rp", addr="127.0.0.1:0",
                         edge="edge1", cost_model=CM2, wire_health=FAST,
                         peaks=PEAKS)
assert rp_plan.cut == 2, f"repartition phase plan chose {rp_plan.cut}"
dep = PartitionDeployment(rp_plan).start()
try:
    obs_util.publish_wire_health(dict(FAST), addr=dep.addr)
    mon = RepartitionMonitor(dep, peaks=PEAKS)
    assert mon.evaluate_once() is None, "steady state must not trigger"
    obs_util.publish_wire_health(
        {"put_150k_ms": 50.0, "dispatch_ms": 5.0}, addr=dep.addr)
    reason = mon.evaluate_once()
    assert reason and "regime flip" in reason, f"no flip trigger: {reason}"
    assert dep.plan.cut is None and dep.worker is None
    assert dep.redeploys == 1, f"redeploys={dep.redeploys}"
    assert mon.evaluate_once() is None, "flip must trigger exactly once"
    assert mon.triggers == 1
    print(f"repartition: '{reason}' -> 1 redeploy (all-local), "
          f"second tick quiet")
finally:
    dep.stop()
    obs_util.reset_wire_health()
print("partition smoke OK: planner-pinned split, subprocess fragment "
      "exact through 2 seeded drops, merged trace with hop arrows, "
      "regime flip = exactly 1 repartition")
PY

run_step "SLO gate (loadgen ci-slo: flooding tenant shed typed, well-behaved p99 held, ledger exact)" \
  python - <<'PY'
# The production-load SLO gate (ISSUE 10): a fixed seeded scenario — an
# in-process 2-worker fleet behind a DRR + per-tenant-rate router, one
# flooding tenant vs three well-behaved tenants on mixed workloads
# (vision / LSTM window / SSD cascade).  The gate asserts the polite
# tenants' p99 and goodput hold while the flood is typed-shed, that
# ZERO requests go lost or unaccounted (client round trips reconcile
# exactly with the router's offered == delivered + shed ledger), and
# that per-trace attribution joined client records with server spans.
import json
import subprocess
import sys

proc = subprocess.run(
    [sys.executable, "tools/loadgen.py", "--scenario", "ci-slo",
     "--seed", "7", "--assert-slo", "--out", "/tmp/ci_slo_report.json"],
    capture_output=True, text=True, timeout=300)
sys.stdout.write(proc.stdout)
sys.stderr.write(proc.stderr)
assert proc.returncode == 0, f"SLO gate failed (rc={proc.returncode})"
report = json.load(open("/tmp/ci_slo_report.json"))
assert report["slo"]["pass"], report["slo"]["checks"]
assert report["ledger"]["exact"], report["ledger"]
assert report["attribution"]["joined"] > 0, report["attribution"]
flood = report["tenants"]["flood"]
wb = {n: t for n, t in report["tenants"].items() if t["well_behaved"]}
assert flood["typed_total"] > 0 and len(wb) == 3
legs = report["attribution"]["legs_ms"]
for leg in ("queue", "device", "serve", "route", "rtt"):
    assert leg in legs, (leg, sorted(legs))
print(f"SLO gate OK: flood shed {flood['typed_total']} typed of "
      f"{flood['offered']}; well-behaved p99s "
      f"{[round(t['latency_ms']['p99_ms'], 1) for t in wb.values()]} ms; "
      f"ledger exact; {report['attribution']['joined']} traces attributed "
      f"(queue/device/serve/route/wire)")
PY

run_step "Forensics smoke (seeded invoke_delay chaos: device verdicts in the gallery, p99.9 exemplar joins its flight dump, /alerts fires then resolves, ledger exact)" \
  python - <<'PY'
# Tail-forensics end-to-end (ISSUE 18): seeded invoke_delay@filter
# chaos under the ci-slo loadgen fleet must produce (a) >=1 gallery
# capture whose typed verdict is `device` — the cost-model root-cause
# chain working against a known-injected device stall; (b) the scraped
# p99.9 exemplar's trace id present in a captured flight dump — the
# scrape->trace join the exemplars exist for; (c) the SLO burn-rate
# alert firing on the run's histogram and resolving once the bad
# window drains; (d) an exact ledger — forensics must observe, never
# perturb.
import json
import os
import shutil
import sys
import time
import urllib.request

sys.path.insert(0, "tools")

GDIR = "/tmp/ci_forensics"
shutil.rmtree(GDIR, ignore_errors=True)
os.environ["NNSTPU_OBS_FORENSICS_DIR"] = GDIR
os.environ["NNSTPU_OBS_FORENSICS_MIN_SAMPLES"] = "24"
os.environ["NNSTPU_SLO_OBJECTIVES"] = "lgci:{pipeline=lg-ci-slo}<50ms@0.999"
os.environ["NNSTPU_SLO_FAST_WINDOW_S"] = "2"
os.environ["NNSTPU_SLO_SLOW_WINDOW_S"] = "4"
os.environ["NNSTPU_SLO_FAST_BURN"] = "2"
os.environ["NNSTPU_SLO_SLOW_BURN"] = "1"
os.environ["NNSTPU_SLO_EVAL_INTERVAL_S"] = "0"

import loadgen  # noqa: E402
from nnstreamer_tpu import faults  # noqa: E402
from nnstreamer_tpu.obs.export import MetricsServer  # noqa: E402
from nnstreamer_tpu.obs.metrics import REGISTRY  # noqa: E402

faults.install("invoke_delay@filter:after=60,every=40,count=6,ms=80",
               seed=7)
try:
    report = loadgen.run_scenario("ci-slo", seed=7, duration_s=2.5)
finally:
    faults.deactivate()

# (d) ledger exact under chaos
assert report["ledger"]["exact"], report["ledger"]

# (a) device verdicts in the bounded gallery
fx = report["forensics"]
assert fx["scored"] > 24 and not fx["warming"], fx
assert fx["outliers"].get("device", 0) >= 1, fx["outliers"]
docs = [json.load(open(os.path.join(GDIR, f)))
        for f in sorted(os.listdir(GDIR)) if f.endswith(".forensic.json")]
dev = [d for d in docs if d["verdict"] == "device"]
assert dev, [d["verdict"] for d in docs]
assert fx["gallery"]["entries"] == len(docs) > 0, fx["gallery"]

# (b) the p99.9 exemplar: highest non-empty bucket's exemplar across
# the run's histogram children must name a trace whose flight dump was
# captured
hist = REGISTRY.get("nnstpu_e2e_latency_ms")
best = None  # (bucket_index, value, trace_id)
for key, child in hist.children():
    if key and key[0] != "lg-ci-slo":
        continue
    for i, ex in enumerate(child.exemplars()):
        if ex is not None and (best is None or (i, ex[1]) >
                               (best[0], best[1])):
            best = (i, ex[1], ex[0])
assert best is not None, "no exemplar stamped"
tail_tid = f"{best[2]:x}"
captured_tids = {d["trace_id"] for d in docs}
assert tail_tid in captured_tids, (tail_tid, captured_tids)
cap = next(d for d in docs if d["trace_id"] == tail_tid)
assert any(e.get("args", {}).get("trace_id") == tail_tid
           for e in cap["flight"]["traceEvents"]), "flight dump empty"

# (c) burn-rate alert: the server's scrape-time engine sees the run's
# bad deltas at first /alerts, then resolves once the windows drain
srv = MetricsServer(port=0, registry=REGISTRY).start()
try:
    url = f"http://127.0.0.1:{srv.port}/alerts"
    doc = json.loads(urllib.request.urlopen(url).read())
    assert doc["firing"] == ["lgci"], doc
    assert doc["objectives"]["lgci"]["severity"] == "page", doc
    deadline = time.time() + 15
    while True:
        time.sleep(1.0)
        doc = json.loads(urllib.request.urlopen(url).read())
        if not doc["firing"]:
            break
        assert time.time() < deadline, f"alert never resolved: {doc}"
    assert doc["objectives"]["lgci"]["transitions"] == 2, doc
    text = urllib.request.urlopen(
        f"http://127.0.0.1:{srv.port}/metrics?exemplars=1"
    ).read().decode()
    assert f'# {{trace_id="{tail_tid}"}}' in text
    assert ('nnstpu_slo_alert_transitions_total{'
            'objective="lgci",state="resolved"} 1') in text
finally:
    srv.stop()

print(f"forensics smoke OK: {fx['outliers']} outliers, "
      f"{len(docs)} captures ({len(dev)} device-verdict), p99.9 exemplar "
      f"{tail_tid} joined its flight dump, alert fired (page) and "
      f"resolved, ledger exact")
PY

run_step "Profiling smoke (/profile capture joined to the cost registry, HBM series, watchdog auto-capture on injected regression, gallery idempotence)" \
  env NNSTPU_TRACERS="spans,device" NNSTPU_METRICS_PORT=0 \
      NNSTPU_OBS_PROFILE_DIR=/tmp/ci_profile_gallery \
      NNSTPU_OBS_PROFILE_KEEP=4 \
      NNSTPU_OBS_PROFILE_AUTO=true \
      NNSTPU_OBS_PROFILE_AUTO_SECONDS=0.5 \
      NNSTPU_OBS_PROFILE_AUTO_COOLDOWN_S=0 \
      NNSTPU_OBS_PROFILE_MIN_SAMPLES=8 \
  python - <<'PY'
# Deep-profiling lane end-to-end (ISSUE 20): (a) GET /profile?seconds=1
# against a serving CPU pipeline must produce an on-disk artifact and a
# parsed op table whose executable fingerprints JOIN the cost registry;
# (b) the scrape must carry the per-executable HBM series recorded at
# compile time; (c) a fault-injected device-time regression (the chaos
# engine's invoke_delay rule, routed through jax.pure_callback so the
# sleep lands INSIDE device execution where the DegradeDetector
# watches) must auto-trigger a watchdog capture; (d) the gallery must
# be idempotent across two runs — a rescan sees the same entries and
# keeps honoring the bound.
import json
import os
import shutil
import time
import urllib.request

import jax
jax.config.update('jax_platforms', 'cpu')
import numpy as np

from nnstreamer_tpu import Pipeline, faults
from nnstreamer_tpu.backends.jax_backend import JaxModel
from nnstreamer_tpu.elements.filter import TensorFilter
from nnstreamer_tpu.elements.sink import TensorSink
from nnstreamer_tpu.elements.testsrc import DataSrc
from nnstreamer_tpu.obs import export, profiler
from nnstreamer_tpu.obs.util import cost_entries
from nnstreamer_tpu.obs.watchdog import PipelineWatchdog
from nnstreamer_tpu.spec import TensorSpec, TensorsSpec

GDIR = "/tmp/ci_profile_gallery"
shutil.rmtree(GDIR, ignore_errors=True)
profiler.reset_gallery()


def host_op(x):
    # the chaos point: with no rule armed this is a cheap pacing sleep;
    # an installed invoke_delay@devcb rule sleeps HERE, inside the
    # device computation
    faults.maybe_invoke("devcb")
    time.sleep(0.02)
    return np.asarray(x) * 2


def make_pipeline(name, frames):
    model = JaxModel(
        apply=lambda p_, x: jax.pure_callback(
            host_op, jax.ShapeDtypeStruct(x.shape, x.dtype), x),
        input_spec=TensorsSpec.of(TensorSpec(dtype=np.float32, shape=(8,))))
    got = []
    p = Pipeline(name=name)
    src = p.add(DataSrc(
        data=[np.full(8, i, np.float32) for i in range(frames)], name="s"))
    filt = p.add(TensorFilter(framework="jax", model=model, name="devcb"))
    p.link_chain(src, filt, p.add(TensorSink(callback=got.append,
                                             name="out")))
    return p, got


# -- (a) on-demand /profile against a serving pipeline ------------------
p, got = make_pipeline("ci_prof", frames=120)
p.start()
try:
    server = export._server
    assert server is not None, \
        "NNSTPU_METRICS_PORT did not start the endpoint"
    while len(got) < 5:
        time.sleep(0.02)
    with urllib.request.urlopen(
            f"http://{server.host}:{server.port}/profile?seconds=1",
            timeout=60) as resp:
        summary = json.loads(resp.read())
    deadline = time.time() + 120
    while len(got) < 120 and time.time() < deadline:
        time.sleep(0.05)
    assert len(got) == 120, len(got)
finally:
    p.stop()
assert summary["trigger"] == "http", summary["trigger"]
assert summary["ops_total"] > 0, summary
assert os.path.isdir(summary["artifact_dir"])
assert profiler.find_xplane_files(summary["artifact_dir"]), \
    "no raw xplane artifacts on disk"
assert os.path.exists(summary["summary_path"])
fps = set(summary["executables"])
assert fps, "no executable fingerprints observed during the window"
registry_keys = set(cost_entries())
assert fps <= registry_keys, (fps, registry_keys)
attributed = {row.get("executable") for row in summary["ops"]}
assert attributed <= registry_keys | {""}, attributed
assert attributed & registry_keys, \
    "op table rows did not join the cost registry"

# -- (b) compile-time HBM series on the scrape --------------------------
with urllib.request.urlopen(server.url, timeout=30) as resp:
    body = resp.read().decode("utf-8")
assert "nnstpu_executable_hbm_bytes" in body, body[:400]
hbm_lines = [l for l in body.splitlines()
             if l.startswith("nnstpu_executable_hbm_bytes{")]
assert any(f'executable="{fp}"' in l for fp in fps for l in hbm_lines), \
    hbm_lines[:5]
assert "nnstpu_op_time_us" in body
assert 'nnstpu_profile_captures_total{trigger="http",outcome="ok"}' in body

# -- (c) watchdog auto-capture on the injected regression ---------------
# ~30 clean baseline frames arm the Welford baseline (min_samples=8),
# then 8 injected 200ms delays inside device execution blow the
# perfdiff noise band
faults.install("invoke_delay@devcb:after=30,every=1,count=8,ms=200",
               seed=7)
try:
    p2, got2 = make_pipeline("ci_prof_auto", frames=60)
    wd = p2.attach_tracer(PipelineWatchdog(interval_s=0.05))
    p2.start()
    try:
        assert wd._profile_detector is not None, \
            "NNSTPU_OBS_PROFILE_AUTO=true did not arm the detector"
        deadline = time.time() + 120
        while time.time() < deadline:
            with wd._lock:
                if wd._auto_captures >= 1:
                    break
            time.sleep(0.05)
        with wd._lock:
            auto = wd._auto_captures
        assert auto >= 1, "watchdog never auto-captured on the regression"
    finally:
        p2.stop()
finally:
    faults.deactivate()
wd_caps = [s for s in profiler.recent_captures()
           if s["trigger"] == "watchdog"]
assert wd_caps, "no watchdog-triggered capture banked"
assert wd.summary()["profile_auto"]["captures"] >= 1

# -- (d) gallery idempotence across two runs ----------------------------
before = profiler.gallery().entries()
assert before and len(before) <= 4, before
profiler.reset_gallery()  # "restart": force a rescan from disk
after = profiler.gallery().entries()
assert after == before, (before, after)
profiler.capture_profile(seconds=0.1)
assert len(profiler.gallery().entries()) <= 4

export.shutdown_server()
print(f"profiling smoke OK: /profile joined {len(fps)} fingerprint(s) to "
      f"the cost registry, {len(hbm_lines)} HBM series, "
      f"{auto} watchdog auto-capture(s), gallery stable at "
      f"{len(after)} entries")
PY

run_step "Bench smoke (final JSON line parses, rc=0)" \
  bash -c '
    env BENCH_FRAMES=10 BENCH_QUANT_FRAMES=4 BENCH_BASELINE_FRAMES=3 \
        BENCH_MUX_FRAMES=3 BENCH_MUX_STREAMS=2 BENCH_MUX_SWEEP=2 \
        BENCH_SSD_FRAMES=3 BENCH_POSE_FRAMES=3 BENCH_LSTM_STEPS=10 \
        BENCH_SEQ_WINDOWS=3 BENCH_MFU_BATCHES=8 BENCH_BREAKDOWN_FRAMES=6 \
        BENCH_CASCADE_FRAMES=2 BENCH_PARTITION_FRAMES=3 \
        BENCH_PROBE_TIMEOUT=10 BENCH_BUDGET_S=1200 \
        BENCH_NOTES_PATH=/tmp/ci_bench_notes.md \
        BENCH_PARTIAL_PATH=/tmp/ci_bench_partial.json \
    python bench.py > /tmp/ci_bench_smoke.out \
    && python tools/check_bench_final.py /tmp/ci_bench_smoke.out'

echo "=== CI RESULT: PASS ===" | tee -a "$LOG"
