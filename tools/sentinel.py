#!/usr/bin/env python
"""Benchmark sentinel: opportunistic mfu.ladder runs on wire recovery.

The tunneled chip's host→device wire oscillates between a fast regime
(~0.3 ms / 150 KB) and a sick one (~30 ms) on a minutes timescale, so
the healthy windows where ladder evidence CAN be measured rarely line
up with an operator running ``bench.py`` by hand.  This daemon closes
that gap:

- poll ``probe_wire_health`` every ``--interval`` seconds, publish each
  probe as the live ``nnstpu_wire_*`` gauges (same path the bench legs
  stamp with), and classify it with ``wire_regime``;
- on a sick→healthy regime flip — and ONLY on the flip edge, never
  while the wire merely stays healthy — trigger exactly one
  ``bench.sentinel_ladder_run()``: the mfu.ladder leg, measured inside
  the open window, banked best-of into BENCH_TPU_CACHE.json with a
  ``provenance: {source: sentinel}`` stamp so cache readers can tell
  opportunistic evidence from operator-launched runs;
- export ``nnstpu_sentinel_polls_total{regime}`` and
  ``nnstpu_sentinel_triggers_total`` so a scrape shows the sentinel is
  alive and how often windows actually open.

Run it: ``python -m tools.sentinel --interval 60`` (or
``python tools/sentinel.py``).  ``--max-polls N`` bounds the loop (CI);
``--dry-run`` feeds a canned sick→healthy probe sequence through the
real flip detector and trigger path — with ``BENCH_MFU_LADDER_ON_CPU=1``
(+ ``--tiny-ladder``) that exercises measurement and provenance banking
end-to-end on a CPU host.

The flip detector and trigger are injectable (``probe_fn`` /
``trigger_fn``) so tests drive fake probe sequences without touching a
device; ``tests/test_sentinel.py`` pins the exactly-one-trigger
contract.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from nnstreamer_tpu.obs import util as obs_util  # noqa: E402
from nnstreamer_tpu.obs.metrics import REGISTRY  # noqa: E402


def _default_probe():
    return obs_util.probe_wire_health(n=5)


def _default_trigger():
    import bench

    return bench.sentinel_ladder_run()


class Sentinel:
    """The poll → classify → flip-edge-trigger loop.

    ``probe_fn`` returns a wire-health dict (``{"put_150k_ms": ...}``)
    or raises; ``trigger_fn`` runs the ladder leg and returns its
    result dict.  Both default to the real thing and are injectable
    for tests.  One trigger per sick→healthy edge: a wire that stays
    healthy for hours re-triggers nothing until it gets sick and
    recovers again.
    """

    def __init__(self, probe_fn=None, trigger_fn=None, interval_s=60.0,
                 registry=None, publish=True):
        self.probe_fn = probe_fn or _default_probe
        self.trigger_fn = trigger_fn or _default_trigger
        self.interval_s = float(interval_s)
        self.publish = publish
        registry = registry if registry is not None else REGISTRY
        self._polls = registry.counter(
            "nnstpu_sentinel_polls_total",
            "Wire-health polls by the benchmark sentinel, by regime "
            "(fast/slow/error)", ("regime",))
        self._triggers = registry.counter(
            "nnstpu_sentinel_triggers_total",
            "mfu.ladder runs triggered by sick-to-healthy wire flips")
        self._prev_regime = None
        self._stop = threading.Event()
        self._thread = None
        self.polls = 0
        self.triggers = []  # [(poll index, ladder result dict)]

    # -- one poll ----------------------------------------------------------

    def poll_once(self) -> dict:
        """Probe, classify, publish, and fire the trigger iff this poll
        completes a sick→healthy edge.  Returns the poll record."""
        self.polls += 1
        record = {"poll": self.polls, "triggered": False}
        try:
            health = self.probe_fn()
            regime = obs_util.wire_regime(health.get("put_150k_ms"))
        except Exception as exc:  # noqa: BLE001 — a dead probe is a datum
            health, regime = None, "error"
            record["error"] = repr(exc)[:200]
        record["regime"] = regime
        if health is not None:
            record["put_150k_ms"] = health.get("put_150k_ms")
            if self.publish:
                try:
                    obs_util.publish_wire_health(health)
                except Exception:  # noqa: BLE001 — publish is best-effort
                    pass
        self._polls.inc(regime=regime)
        if self._prev_regime == "slow" and regime == "fast":
            # the edge: the window just opened — measure NOW
            record["triggered"] = True
            self._triggers.inc()
            try:
                result = self.trigger_fn()
            except Exception as exc:  # noqa: BLE001 — sentinel must survive
                result = {"error": repr(exc)[:200]}
            self.triggers.append((self.polls, result))
            record["ladder"] = result
        # an errored probe does not count as a regime: the NEXT valid
        # sick reading re-arms normally, but error→fast is not a flip
        self._prev_regime = regime if regime in ("slow", "fast") else None
        return record

    # -- loop --------------------------------------------------------------

    def run(self, max_polls=None, on_poll=None) -> int:
        """Poll until stopped (or ``max_polls`` reached); returns the
        number of polls performed."""
        n = 0
        while not self._stop.is_set():
            rec = self.poll_once()
            n += 1
            if on_poll is not None:
                on_poll(rec)
            if max_polls is not None and n >= max_polls:
                break
            if self._stop.wait(self.interval_s):
                break
        return n

    def start(self, max_polls=None) -> None:
        """Run the loop on a daemon thread (embedded/supervised use)."""
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self.run, kwargs={"max_polls": max_polls},
            name="bench-sentinel", daemon=True)
        self._thread.start()

    def stop(self, timeout=5.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)


# ---------------------------------------------------------------- dry run


def _dry_run_probe_fn():
    """A canned sick→healthy sequence: one slow probe, then fast ones —
    the real flip detector sees exactly one edge."""
    seq = iter([30.0, 0.3])
    last = [0.3]

    def probe():
        ms = next(seq, last[0])
        return {"put_150k_ms": ms, "put_150k_ms_p95": ms,
                "dispatch_ms": 0.01, "n": 1, "dry_run": True}

    return probe


def _tiny_ladder_trigger():
    """Shrink the ladder grid to one 32×32 fp32/mesh-1 cell so the CI
    dry-run leg measures + banks in seconds, not minutes.  The cell is
    measured with ``BENCH_LADDER_PROFILE=1``: the sick→healthy window
    this trigger fires in is exactly when op-level evidence is worth
    banking, so the cell carries a deep-profiling op table next to its
    MFU sample, provenance-stamped like everything else the sentinel
    banks (a busy capture window degrades to an unprofiled cell)."""
    import bench

    bench.LADDER_BATCHES = (8,)
    bench.LADDER_DTYPES = ("fp32",)
    bench.LADDER_MESHES = (1,)
    bench.LADDER_TARGETS = {8: 0.001}
    orig_point = bench.ladder_point
    bench.ladder_point = (
        lambda batch, dtype, ndev, image_size=224:
        orig_point(batch, dtype, ndev, image_size=32))
    old_profile = os.environ.get("BENCH_LADDER_PROFILE")
    os.environ["BENCH_LADDER_PROFILE"] = "1"
    try:
        return bench.sentinel_ladder_run()
    finally:
        if old_profile is None:
            os.environ.pop("BENCH_LADDER_PROFILE", None)
        else:
            os.environ["BENCH_LADDER_PROFILE"] = old_profile


# -------------------------------------------------------------------- CLI


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="wire-health sentinel: poll the wire, auto-run the "
                    "mfu.ladder bench leg on sick→healthy recovery")
    ap.add_argument("--interval", type=float, default=60.0,
                    help="seconds between wire probes (default 60)")
    ap.add_argument("--max-polls", type=int, default=None,
                    help="stop after N polls (default: run forever)")
    ap.add_argument("--once", action="store_true",
                    help="single poll, then exit")
    ap.add_argument("--dry-run", action="store_true",
                    help="feed a canned sick→healthy probe sequence "
                         "through the real flip detector + trigger "
                         "(2 polls, no device probing)")
    ap.add_argument("--tiny-ladder", action="store_true",
                    help="shrink the triggered ladder to one tiny cell "
                         "(CI smoke; implies the measurement still runs "
                         "for real — pair with BENCH_MFU_LADDER_ON_CPU=1 "
                         "off-accelerator)")
    args = ap.parse_args(argv)

    probe_fn = None
    trigger_fn = _tiny_ladder_trigger if args.tiny_ladder else None
    max_polls = 1 if args.once else args.max_polls
    interval = args.interval
    if args.dry_run:
        probe_fn = _dry_run_probe_fn()
        max_polls = 2 if max_polls is None else max_polls
        interval = 0.0

    s = Sentinel(probe_fn=probe_fn, trigger_fn=trigger_fn,
                 interval_s=interval)

    def on_poll(rec):
        print(json.dumps(rec, default=str), flush=True)

    try:
        s.run(max_polls=max_polls, on_poll=on_poll)
    except KeyboardInterrupt:
        pass
    if args.dry_run and len(s.triggers) != 1:
        print(f"# dry-run expected exactly 1 trigger, got "
              f"{len(s.triggers)}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
