#!/usr/bin/env python
"""Randomized soak campaign: many short randomized pipelines, exactness
checked on every frame.  A failure prints the seed for a one-line repro:

    python tools/soak_campaign.py --seed N

Topology templates (drawn at random per iteration):
  linear   src → [transform] → [upload+queue | dynbatch | both] → filter → sink
  tee      src → tee → (queued filter) × 2..3 branches
  mux      src×K → mux → batch → filter → unbatch → demux → sink×K
  repo     LSTM-style state cycle through repo slots
  trainer  (x, y) stream into tensor_trainer, loss must stay finite

Usage: python tools/soak_campaign.py [--minutes 10] [--seed N]
"""

import argparse
import os
import sys
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")  # soak targets the graph, not the chip

import numpy as np  # noqa: E402


def run_linear(rng):
    import jax.numpy as jnp

    from nnstreamer_tpu import Pipeline
    from nnstreamer_tpu.backends.jax_backend import JaxModel
    from nnstreamer_tpu.buffer import Frame
    from nnstreamer_tpu.elements.dynbatch import DynBatch, DynUnbatch
    from nnstreamer_tpu.elements.filter import TensorFilter
    from nnstreamer_tpu.elements.queue import Queue
    from nnstreamer_tpu.elements.sink import TensorSink
    from nnstreamer_tpu.elements.testsrc import DataSrc
    from nnstreamer_tpu.elements.upload import TensorUpload

    n = int(rng.integers(20, 120))
    d = int(rng.integers(2, 16))
    scale = float(rng.uniform(0.5, 3.0))
    use_upload = bool(rng.integers(0, 2))
    use_dyn = bool(rng.integers(0, 2))
    frames = [Frame.of(np.full((d,), float(i), np.float32), pts=i)
              for i in range(n)]
    if use_dyn:
        model = JaxModel(apply=lambda p, x: x * scale,
                         input_spec=None)
    else:
        model = JaxModel(apply=lambda p, x: x * scale)
    got = []
    p = Pipeline()
    chain = [p.add(DataSrc(data=frames))]
    if use_dyn:
        chain.append(p.add(DynBatch(max_batch=int(2 ** rng.integers(1, 4)))))
    if use_upload:
        chain.append(p.add(TensorUpload()))
        chain.append(p.add(Queue(max_size_buffers=8)))
    chain.append(p.add(TensorFilter(framework="jax", model=model)))
    if use_dyn:
        chain.append(p.add(DynUnbatch()))
    sink = p.add(TensorSink())
    sink.connect("new-data", lambda f: got.append(np.asarray(f.tensor(0))))
    chain.append(sink)
    p.link_chain(*chain)
    p.run(timeout=120)
    assert len(got) == n, f"linear: {len(got)}/{n} frames"
    for i, a in enumerate(got):
        np.testing.assert_allclose(a, i * scale, rtol=1e-5,
                                   err_msg=f"frame {i}")


def run_tee(rng):
    from nnstreamer_tpu import Pipeline
    from nnstreamer_tpu.backends.jax_backend import JaxModel
    from nnstreamer_tpu.buffer import Frame
    from nnstreamer_tpu.elements.filter import TensorFilter
    from nnstreamer_tpu.elements.queue import Queue
    from nnstreamer_tpu.elements.sink import TensorSink
    from nnstreamer_tpu.elements.tee import Tee
    from nnstreamer_tpu.elements.testsrc import DataSrc

    n = int(rng.integers(20, 100))
    branches = int(rng.integers(2, 4))
    frames = [Frame.of(np.full((4,), float(i), np.float32), pts=i)
              for i in range(n)]
    got = [[] for _ in range(branches)]
    p = Pipeline()
    src = p.add(DataSrc(data=frames))
    tee = p.add(Tee())
    p.link(src, tee)
    for b in range(branches):
        q = p.add(Queue(max_size_buffers=int(rng.integers(2, 16))))
        f = p.add(TensorFilter(
            framework="jax",
            model=JaxModel(apply=lambda pp, x, b=b: x + float(b)),
        ))
        s = p.add(TensorSink())
        s.connect("new-data",
                  lambda fr, b=b: got[b].append(np.asarray(fr.tensor(0))))
        p.link(tee, q)
        p.link_chain(q, f, s)
    p.run(timeout=120)
    for b in range(branches):
        assert len(got[b]) == n, f"tee branch {b}: {len(got[b])}/{n}"
        for i, a in enumerate(got[b]):
            np.testing.assert_allclose(a, i + b, rtol=1e-5)


def run_mux(rng):
    from nnstreamer_tpu import Pipeline, make
    from nnstreamer_tpu.backends.jax_backend import JaxModel
    from nnstreamer_tpu.elements.batch import TensorBatch, TensorUnbatch
    from nnstreamer_tpu.elements.demux import TensorDemux
    from nnstreamer_tpu.elements.filter import TensorFilter
    from nnstreamer_tpu.elements.sink import TensorSink
    from nnstreamer_tpu.elements.testsrc import DataSrc

    k = int(rng.integers(2, 5))
    per = int(rng.integers(10, 40))
    d = int(rng.integers(2, 8))
    got = {s: [] for s in range(k)}
    p = Pipeline()
    mux = p.add(make("tensor_mux", sync_mode="nosync"))
    for s in range(k):
        src = p.add(DataSrc(
            data=[np.full((d,), 100.0 * s + t, np.float32)
                  for t in range(per)], name=f"s{s}"))
        p.link(src, f"{mux.name}.sink_{s}")
    batch = p.add(TensorBatch())
    filt = p.add(TensorFilter(
        framework="jax", model=JaxModel(apply=lambda pp, x: x * 2.0)))
    unb = p.add(TensorUnbatch())
    demux = p.add(TensorDemux())
    p.link_chain(mux, batch, filt, unb, demux)
    for s in range(k):
        sink = p.add(TensorSink(name=f"o{s}"))
        sink.connect("new-data",
                     lambda fr, s=s: got[s].append(np.asarray(fr.tensor(0))))
        p.link(f"{demux.name}.src_{s}", sink)
    p.run(timeout=120)
    for s in range(k):
        assert len(got[s]) == per, f"mux stream {s}: {len(got[s])}/{per}"
        for t, a in enumerate(got[s]):
            np.testing.assert_allclose(a, 2.0 * (100.0 * s + t), rtol=1e-5)


def run_repo(rng):
    import bench

    steps = int(rng.integers(10, 40))
    sps = bench.run_lstm_recurrence_fps(steps, hidden=int(rng.integers(8, 64)))
    assert sps > 0


def run_trainer(rng):
    import jax.numpy as jnp

    from nnstreamer_tpu import Pipeline
    from nnstreamer_tpu.backends.jax_backend import JaxModel
    from nnstreamer_tpu.buffer import Frame
    from nnstreamer_tpu.elements.sink import TensorSink
    from nnstreamer_tpu.elements.testsrc import DataSrc
    from nnstreamer_tpu.elements.trainer import TensorTrainer
    from nnstreamer_tpu.spec import TensorSpec, TensorsSpec

    n = int(rng.integers(10, 40))
    d = int(rng.integers(2, 8))
    b = int(rng.integers(1, 4)) * 2
    w = rng.standard_normal((d, 2)).astype(np.float32)
    frames = []
    for i in range(n):
        x = rng.standard_normal((b, d)).astype(np.float32)
        frames.append(Frame.of(x, x @ w, pts=i))
    model = JaxModel(
        apply=lambda p, x: x @ p, params=jnp.zeros((d, 2), jnp.float32),
        input_spec=TensorsSpec.of(TensorSpec(dtype=np.float32, shape=(b, d))),
    )
    curve = []
    p = Pipeline()
    src = p.add(DataSrc(data=frames))
    tr = p.add(TensorTrainer(model=model, loss="mse", optimizer="adam,lr=0.05"))
    sink = p.add(TensorSink())
    sink.connect("new-data",
                 lambda f: curve.append(float(np.asarray(f.tensor(0)))))
    p.link_chain(src, tr, sink)
    p.run(timeout=120)
    assert len(curve) == n and all(np.isfinite(v) for v in curve)


TEMPLATES = [run_linear, run_tee, run_mux, run_repo, run_trainer]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--minutes", type=float, default=10.0)
    ap.add_argument("--seed", type=int, default=None)
    args = ap.parse_args()

    if args.seed is not None:  # single-iteration repro
        rng = np.random.default_rng(args.seed)
        fn = TEMPLATES[int(rng.integers(0, len(TEMPLATES)))]
        print(f"repro seed={args.seed}: {fn.__name__}")
        fn(rng)
        print("OK")
        return 0

    t_end = time.time() + args.minutes * 60
    i = fails = 0
    base = int(time.time())
    while time.time() < t_end:
        seed = base + i
        rng = np.random.default_rng(seed)
        fn = TEMPLATES[int(rng.integers(0, len(TEMPLATES)))]
        try:
            fn(rng)
            print(f"[{i}] {fn.__name__} seed={seed} OK", flush=True)
        except Exception:
            fails += 1
            print(f"[{i}] {fn.__name__} seed={seed} FAILED", flush=True)
            traceback.print_exc()
        i += 1
    print(f"campaign done: {i} iterations, {fails} failures")
    return 1 if fails else 0


if __name__ == "__main__":
    sys.exit(main())
