#!/usr/bin/env python
"""Randomized soak campaign: many short randomized pipelines, exactness
checked on every frame.  A failure prints the seed for a one-line repro:

    python tools/soak_campaign.py --seed N

Topology templates (drawn at random per iteration):
  linear        src → [upload+queue | dynbatch | both] → filter → sink
  tee           src → tee → (queued filter) × 2..3 branches
  mux           src×K → mux → batch → filter → unbatch → demux → sink×K
  repo          LSTM-style state cycle through repo slots
  trainer       (x, y) stream into tensor_trainer, loss must stay finite
  renegotiation mid-stream shape changes through random chains
  valve         event-driven valve close/reopen; order + exactness held
  interrupt     pipeline.stop() from another thread mid-stream (30s bound)
  query         TCP offload: QueryServer + 1-3 concurrent client pipelines
  sparse        tensor_sparse_enc→dec round-trip on random shapes/densities

Usage: python tools/soak_campaign.py [--minutes 10] [--seed N]
"""

import argparse
import os
import sys
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")  # soak targets the graph, not the chip

import numpy as np  # noqa: E402


def run_linear(rng):
    import jax.numpy as jnp

    from nnstreamer_tpu import Pipeline
    from nnstreamer_tpu.backends.jax_backend import JaxModel
    from nnstreamer_tpu.buffer import Frame
    from nnstreamer_tpu.elements.dynbatch import DynBatch, DynUnbatch
    from nnstreamer_tpu.elements.filter import TensorFilter
    from nnstreamer_tpu.elements.queue import Queue
    from nnstreamer_tpu.elements.sink import TensorSink
    from nnstreamer_tpu.elements.testsrc import DataSrc
    from nnstreamer_tpu.elements.upload import TensorUpload

    n = int(rng.integers(20, 120))
    d = int(rng.integers(2, 16))
    scale = float(rng.uniform(0.5, 3.0))
    use_upload = bool(rng.integers(0, 2))
    use_dyn = bool(rng.integers(0, 2))
    frames = [Frame.of(np.full((d,), float(i), np.float32), pts=i)
              for i in range(n)]
    if use_dyn:
        model = JaxModel(apply=lambda p, x: x * scale,
                         input_spec=None)
    else:
        model = JaxModel(apply=lambda p, x: x * scale)
    got = []
    p = Pipeline()
    chain = [p.add(DataSrc(data=frames))]
    if use_dyn:
        chain.append(p.add(DynBatch(max_batch=int(2 ** rng.integers(1, 4)))))
    if use_upload:
        chain.append(p.add(TensorUpload()))
        chain.append(p.add(Queue(max_size_buffers=8)))
    chain.append(p.add(TensorFilter(framework="jax", model=model)))
    if use_dyn:
        chain.append(p.add(DynUnbatch()))
    sink = p.add(TensorSink())
    sink.connect("new-data", lambda f: got.append(np.asarray(f.tensor(0))))
    chain.append(sink)
    p.link_chain(*chain)
    p.run(timeout=120)
    assert len(got) == n, f"linear: {len(got)}/{n} frames"
    for i, a in enumerate(got):
        np.testing.assert_allclose(a, i * scale, rtol=1e-5,
                                   err_msg=f"frame {i}")


def run_tee(rng):
    from nnstreamer_tpu import Pipeline
    from nnstreamer_tpu.backends.jax_backend import JaxModel
    from nnstreamer_tpu.buffer import Frame
    from nnstreamer_tpu.elements.filter import TensorFilter
    from nnstreamer_tpu.elements.queue import Queue
    from nnstreamer_tpu.elements.sink import TensorSink
    from nnstreamer_tpu.elements.tee import Tee
    from nnstreamer_tpu.elements.testsrc import DataSrc

    n = int(rng.integers(20, 100))
    branches = int(rng.integers(2, 4))
    frames = [Frame.of(np.full((4,), float(i), np.float32), pts=i)
              for i in range(n)]
    got = [[] for _ in range(branches)]
    p = Pipeline()
    src = p.add(DataSrc(data=frames))
    tee = p.add(Tee())
    p.link(src, tee)
    for b in range(branches):
        q = p.add(Queue(max_size_buffers=int(rng.integers(2, 16))))
        f = p.add(TensorFilter(
            framework="jax",
            model=JaxModel(apply=lambda pp, x, b=b: x + float(b)),
        ))
        s = p.add(TensorSink())
        s.connect("new-data",
                  lambda fr, b=b: got[b].append(np.asarray(fr.tensor(0))))
        p.link(tee, q)
        p.link_chain(q, f, s)
    p.run(timeout=120)
    for b in range(branches):
        assert len(got[b]) == n, f"tee branch {b}: {len(got[b])}/{n}"
        for i, a in enumerate(got[b]):
            np.testing.assert_allclose(a, i + b, rtol=1e-5)


def run_mux(rng):
    from nnstreamer_tpu import Pipeline, make
    from nnstreamer_tpu.backends.jax_backend import JaxModel
    from nnstreamer_tpu.elements.batch import TensorBatch, TensorUnbatch
    from nnstreamer_tpu.elements.demux import TensorDemux
    from nnstreamer_tpu.elements.filter import TensorFilter
    from nnstreamer_tpu.elements.sink import TensorSink
    from nnstreamer_tpu.elements.testsrc import DataSrc

    k = int(rng.integers(2, 5))
    per = int(rng.integers(10, 40))
    d = int(rng.integers(2, 8))
    got = {s: [] for s in range(k)}
    p = Pipeline()
    mux = p.add(make("tensor_mux", sync_mode="nosync"))
    for s in range(k):
        src = p.add(DataSrc(
            data=[np.full((d,), 100.0 * s + t, np.float32)
                  for t in range(per)], name=f"s{s}"))
        p.link(src, f"{mux.name}.sink_{s}")
    batch = p.add(TensorBatch())
    filt = p.add(TensorFilter(
        framework="jax", model=JaxModel(apply=lambda pp, x: x * 2.0)))
    unb = p.add(TensorUnbatch())
    demux = p.add(TensorDemux())
    p.link_chain(mux, batch, filt, unb, demux)
    for s in range(k):
        sink = p.add(TensorSink(name=f"o{s}"))
        sink.connect("new-data",
                     lambda fr, s=s: got[s].append(np.asarray(fr.tensor(0))))
        p.link(f"{demux.name}.src_{s}", sink)
    p.run(timeout=120)
    for s in range(k):
        assert len(got[s]) == per, f"mux stream {s}: {len(got[s])}/{per}"
        for t, a in enumerate(got[s]):
            np.testing.assert_allclose(a, 2.0 * (100.0 * s + t), rtol=1e-5)


def run_repo(rng):
    import bench

    steps = int(rng.integers(10, 40))
    sps = bench.run_lstm_recurrence_fps(steps, hidden=int(rng.integers(8, 64)))
    assert sps > 0


def run_trainer(rng):
    import jax.numpy as jnp

    from nnstreamer_tpu import Pipeline
    from nnstreamer_tpu.backends.jax_backend import JaxModel
    from nnstreamer_tpu.buffer import Frame
    from nnstreamer_tpu.elements.sink import TensorSink
    from nnstreamer_tpu.elements.testsrc import DataSrc
    from nnstreamer_tpu.elements.trainer import TensorTrainer
    from nnstreamer_tpu.spec import TensorSpec, TensorsSpec

    n = int(rng.integers(10, 40))
    d = int(rng.integers(2, 8))
    b = int(rng.integers(1, 4)) * 2
    w = rng.standard_normal((d, 2)).astype(np.float32)
    frames = []
    for i in range(n):
        x = rng.standard_normal((b, d)).astype(np.float32)
        frames.append(Frame.of(x, x @ w, pts=i))
    model = JaxModel(
        apply=lambda p, x: x @ p, params=jnp.zeros((d, 2), jnp.float32),
        input_spec=TensorsSpec.of(TensorSpec(dtype=np.float32, shape=(b, d))),
    )
    curve = []
    p = Pipeline()
    src = p.add(DataSrc(data=frames))
    tr = p.add(TensorTrainer(model=model, loss="mse", optimizer="adam,lr=0.05"))
    sink = p.add(TensorSink())
    sink.connect("new-data",
                 lambda f: curve.append(float(np.asarray(f.tensor(0)))))
    p.link_chain(src, tr, sink)
    p.run(timeout=120)
    assert len(curve) == n and all(np.isfinite(v) for v in curve)


def run_renegotiation(rng):
    """Shape changes mid-stream through a random chain: caps events must
    renegotiate every hop (queue workers, dynbatch worker, backend
    recompiles) without loss or reorder."""
    from nnstreamer_tpu import Pipeline
    from nnstreamer_tpu.backends.jax_backend import JaxModel
    from nnstreamer_tpu.buffer import Frame
    from nnstreamer_tpu.elements.dynbatch import DynBatch, DynUnbatch
    from nnstreamer_tpu.elements.filter import TensorFilter
    from nnstreamer_tpu.elements.queue import Queue
    from nnstreamer_tpu.elements.sink import TensorSink
    from nnstreamer_tpu.elements.testsrc import DataSrc

    phases = int(rng.integers(2, 5))
    per = int(rng.integers(8, 30))
    use_q = bool(rng.integers(0, 2))
    use_dyn = bool(rng.integers(0, 2))
    frames, expect, seq = [], [], 0
    for _ in range(phases):
        shape = tuple(int(rng.integers(2, 5))
                      for _ in range(int(rng.integers(1, 3))))
        for _ in range(per):
            frames.append(Frame.of(np.full(shape, float(seq), np.float32),
                                   pts=seq))
            expect.append(float(seq) * int(np.prod(shape)))
            seq += 1
    model = JaxModel(apply=lambda p, x: (
        x.reshape(x.shape[0], -1).sum(axis=1) if use_dyn
        else x.reshape(-1).sum()[None]
    ))
    got = []
    p = Pipeline()
    chain = [p.add(DataSrc(data=frames))]
    if use_dyn:
        chain.append(p.add(DynBatch(max_batch=4)))
    if use_q:
        chain.append(p.add(Queue(max_size_buffers=8)))
    chain.append(p.add(TensorFilter(framework="jax", model=model)))
    if use_dyn:
        chain.append(p.add(DynUnbatch()))
    sink = p.add(TensorSink())
    sink.connect("new-data",
                 lambda f: got.append(float(np.asarray(f.tensor(0)).reshape(()))))
    chain.append(sink)
    p.link_chain(*chain)
    p.run(timeout=120)
    assert len(got) == seq, f"reneg: {len(got)}/{seq}"
    np.testing.assert_allclose(got, expect, rtol=1e-5)


def run_valve_selector(rng):
    """Flow control under load: a valve toggled mid-stream drops a known
    span; frames that pass must stay exact and ordered."""
    import threading

    from nnstreamer_tpu import Pipeline, make
    from nnstreamer_tpu.buffer import Frame
    from nnstreamer_tpu.elements.queue import Queue
    from nnstreamer_tpu.elements.sink import TensorSink
    from nnstreamer_tpu.elements.testsrc import DataSrc

    n = int(rng.integers(50, 150))
    frames = [Frame.of(np.full((4,), float(i), np.float32), pts=i)
              for i in range(n)]
    got = []
    p = Pipeline()
    src = p.add(DataSrc(data=frames))
    valve = p.add(make("valve"))
    q = p.add(Queue(max_size_buffers=8))
    sink = p.add(TensorSink())
    # event-driven toggling (a wall-clock timer raced the stream on a
    # loaded host): close the valve after the 5th delivered frame,
    # reopen after a few ms — deliveries 1-5 are guaranteed through
    close_at = 5
    reopened = threading.Event()

    def on_frame(f):
        got.append(int(np.asarray(f.tensor(0))[0]))
        if len(got) == close_at and not reopened.is_set():
            valve.drop = True
            threading.Timer(0.01, lambda: (
                setattr(valve, "drop", False), reopened.set()
            )).start()

    sink.connect("new-data", on_frame)
    p.link_chain(src, valve, q, sink)
    p.run(timeout=120)
    # the first close_at deliveries are guaranteed: exactly frames 0..4
    assert got[:close_at] == list(range(close_at)), got[:close_at]
    # whatever arrived must be strictly increasing (order, no dup)
    assert all(b > a for a, b in zip(got, got[1:])), "reorder/dup past valve"
    assert len(got) >= close_at, f"only {len(got)} frames passed the valve"


def run_interrupt(rng):
    """Mid-stream stop: a busy pipeline (queues + filter + dynbatch) is
    stopped from another thread while frames are in flight.  The hunt is
    for shutdown deadlocks — stop() must return promptly."""
    import threading
    import time as _t

    from nnstreamer_tpu import Pipeline
    from nnstreamer_tpu.backends.jax_backend import JaxModel
    from nnstreamer_tpu.buffer import Frame
    from nnstreamer_tpu.elements.dynbatch import DynBatch, DynUnbatch
    from nnstreamer_tpu.elements.filter import TensorFilter
    from nnstreamer_tpu.elements.queue import Queue
    from nnstreamer_tpu.elements.sink import TensorSink
    from nnstreamer_tpu.elements.testsrc import DataSrc

    n = 2000  # more than will ever drain before the stop
    frames = [Frame.of(np.full((8,), float(i), np.float32), pts=i)
              for i in range(n)]
    p = Pipeline()
    chain = [p.add(DataSrc(data=frames))]
    if rng.integers(0, 2):
        chain.append(p.add(DynBatch(max_batch=4)))
        chain.append(p.add(Queue(max_size_buffers=4)))
        chain.append(p.add(TensorFilter(
            framework="jax", model=JaxModel(apply=lambda pp, x: x * 2.0))))
        chain.append(p.add(DynUnbatch()))
    else:
        chain.append(p.add(Queue(max_size_buffers=4)))
        chain.append(p.add(TensorFilter(
            framework="jax", model=JaxModel(apply=lambda pp, x: x * 2.0))))
    sink = p.add(TensorSink())
    chain.append(sink)
    p.link_chain(*chain)
    p.start()
    _t.sleep(float(rng.uniform(0.01, 0.15)))
    done = threading.Event()

    def stopper():
        p.stop()
        done.set()

    # daemon: if stop() truly wedges, the blocked thread must not keep the
    # campaign process alive past its final summary
    th = threading.Thread(target=stopper, daemon=True)
    th.start()
    th.join(timeout=30)
    assert done.is_set(), "pipeline.stop() deadlocked (>30s)"


def run_query(rng):
    """TCP offload under churn: an in-process QueryServer, 1-3 client
    pipelines (threads) with per-stream exactness; random shapes exercise
    the per-spec backend cache."""
    import threading

    from nnstreamer_tpu import Pipeline
    from nnstreamer_tpu.backends.jax_backend import JaxModel
    from nnstreamer_tpu.elements.query import QueryServer, TensorQueryClient
    from nnstreamer_tpu.elements.sink import TensorSink
    from nnstreamer_tpu.elements.testsrc import DataSrc
    from nnstreamer_tpu.spec import TensorSpec, TensorsSpec

    n_clients = int(rng.integers(1, 4))
    per = int(rng.integers(5, 25))
    out_spec = TensorsSpec.of(TensorSpec(dtype=np.float32, shape=None))
    model = JaxModel(apply=lambda p, x: x * 2.0)
    # half the runs turn on cross-client batching (requires batch-dim
    # frames, which these (d0, ...) fills satisfy: rank >= 1)
    batch = int(rng.choice([0, 0, 2, 4]))
    with QueryServer(framework="jax", model=model, batch=batch,
                     batch_window_ms=float(rng.uniform(0.5, 10.0))) as srv:
        results = {}

        def client(k, shape):
            frames = [np.full(shape, float(100 * k + i), np.float32)
                      for i in range(per)]
            got = []
            p = Pipeline()
            src = p.add(DataSrc(data=frames))
            cli = p.add(TensorQueryClient(port=srv.port, out_spec=out_spec))
            sink = p.add(TensorSink())
            sink.connect("new-data",
                         lambda f: got.append(np.asarray(f.tensor(0))))
            p.link_chain(src, cli, sink)
            p.run(timeout=120)
            results[k] = got

        shapes = [tuple(int(rng.integers(2, 5))
                        for _ in range(int(rng.integers(1, 3))))
                  for _ in range(n_clients)]
        ts = [threading.Thread(target=client, args=(k, shapes[k]))
              for k in range(n_clients)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120)
    for k in range(n_clients):
        assert len(results.get(k, [])) == per, f"client {k} incomplete"
        for i, a in enumerate(results[k]):
            np.testing.assert_allclose(a, 2.0 * (100 * k + i), rtol=1e-5)


def run_tensor_if(rng):
    """Value-gating under load: known value stream through tensor_if —
    the surviving set must be exactly the frames matching the predicate."""
    from nnstreamer_tpu import Pipeline
    from nnstreamer_tpu.elements.sink import TensorSink
    from nnstreamer_tpu.elements.tensor_if import TensorIf
    from nnstreamer_tpu.elements.testsrc import DataSrc

    n = int(rng.integers(20, 80))
    thr = float(rng.uniform(0.2, 0.8))
    vals = rng.uniform(0.0, 1.0, n).astype(np.float32)
    got = []
    p = Pipeline()
    src = p.add(DataSrc(data=[np.array([v], np.float32) for v in vals]))
    tif = p.add(TensorIf(compared_value="max", op=">", threshold=thr))
    sink = p.add(TensorSink())
    sink.connect("new-data",
                 lambda f: got.append(float(np.asarray(f.tensor(0))[0])))
    p.link_chain(src, tif, sink)
    p.run(timeout=120)
    want = [float(v) for v in vals if v > thr]
    np.testing.assert_allclose(got, want, rtol=1e-6)
    assert tif.passed == len(want) and tif.dropped == n - len(want)


def run_crop(rng):
    """tensor_crop static mode under randomized regions: every crop in the
    (K,H,W,C) stack must equal its exact numpy slice (zero-pad beyond the
    region count, coordinates clamped into the frame)."""
    from fractions import Fraction

    from nnstreamer_tpu import Pipeline
    from nnstreamer_tpu.elements.crop import TensorCrop
    from nnstreamer_tpu.elements.sink import TensorSink
    from nnstreamer_tpu.elements.testsrc import DataSrc

    n = int(rng.integers(5, 20))
    H = W = int(rng.integers(16, 48))
    cw, ch = int(rng.integers(4, 12)), int(rng.integers(4, 12))
    K = int(rng.integers(1, 4))
    imgs = [rng.integers(0, 256, (H, W, 3)).astype(np.uint8)
            for _ in range(n)]
    # ≥1 row (the spec layer forbids 0-sized dims); zero-area sentinel rows
    # (w/h ≤ 0, the "no detection" encoding) are mixed in deliberately
    regs = []
    for _ in range(n):
        r = rng.integers(-4, max(W, H) + 4, (int(rng.integers(1, K + 2)), 4))
        r = r.astype(np.int32)
        for i in range(len(r)):
            if rng.uniform() < 0.2:
                r[i, 2 + int(rng.integers(0, 2))] = -int(rng.integers(0, 3))
        regs.append(r)
    got = []
    p = Pipeline()
    raw = p.add(DataSrc(data=imgs, rate=Fraction(30)))
    info = p.add(DataSrc(data=regs, rate=Fraction(30)))
    crop = p.add(TensorCrop(name="c", size=f"{cw}:{ch}", num=K))
    sink = p.add(TensorSink())
    sink.connect("new-data", got.append)
    p.link(raw, "c.raw")
    p.link(info, "c.info")
    p.link(crop, sink)
    p.run(timeout=120)
    assert len(got) == n
    for img, r, f in zip(imgs, regs, got):
        out = np.asarray(f.tensor(0))
        assert out.shape == (K, ch, cw, 3)
        valid = [row for row in r if row[2] > 0 and row[3] > 0][:K]
        assert f.meta["tensor_crop"]["regions"] == len(valid)
        for i, row in enumerate(valid):
            x = int(row[0]); y = int(row[1])
            x = max(0, min(x, W - cw)) if W >= cw else 0
            y = max(0, min(y, H - ch)) if H >= ch else 0
            want = np.zeros((ch, cw, 3), np.uint8)
            src_sl = img[y:y + ch, x:x + cw]
            want[:src_sl.shape[0], :src_sl.shape[1]] = src_sl
            np.testing.assert_array_equal(out[i], want)
        for i in range(len(valid), K):
            assert not out[i].any()


def run_rate(rng):
    """tensor_rate invariants on a randomized in/out rate pair: the output
    pts timeline is exactly slotted, counters balance, and the
    down-sampling case never duplicates (nor the up-sampling case drop)."""
    from nnstreamer_tpu import Pipeline
    from nnstreamer_tpu.buffer import Frame
    from nnstreamer_tpu.elements.rate import TensorRate
    from nnstreamer_tpu.elements.sink import TensorSink
    from nnstreamer_tpu.elements.testsrc import DataSrc

    n = int(rng.integers(10, 60))
    fin = int(rng.integers(5, 60))
    fout = int(rng.integers(5, 60))
    dur = 1_000_000_000 // fin
    frames = [Frame.of(np.array([i], np.int32), pts=i * dur, duration=dur)
              for i in range(n)]
    got = []
    p = Pipeline()
    src = p.add(DataSrc(data=frames))
    rate = p.add(TensorRate(framerate=f"{fout}/1"))
    sink = p.add(TensorSink())
    sink.connect("new-data", got.append)
    p.link_chain(src, rate, sink)
    p.run(timeout=120)
    period = 1_000_000_000 // fout
    slots = [f.pts // period for f in got]
    assert slots == sorted(set(slots)), "output slots must be strictly increasing"
    assert all(f.pts % period == 0 for f in got)
    assert rate.in_frames == n
    assert rate.out_frames == len(got) == rate.in_frames - rate.drop + rate.dup
    if fout <= fin:
        assert rate.dup == 0
    if fout >= fin:
        assert rate.drop == 0
    # source values must appear in order (duplication repeats, never reorders)
    vals = [int(np.asarray(f.tensor(0))[0]) for f in got]
    assert vals == sorted(vals)


def run_sparse(rng):
    """tensor_sparse_enc→dec round-trip exactness on randomized shapes,
    dtypes, and densities (including all-zero and fully-dense frames),
    with a queue between the codec halves half the time."""
    from nnstreamer_tpu import Pipeline, make
    from nnstreamer_tpu.buffer import Frame
    from nnstreamer_tpu.elements.queue import Queue
    from nnstreamer_tpu.elements.sink import TensorSink
    from nnstreamer_tpu.elements.testsrc import DataSrc

    n = int(rng.integers(5, 40))
    rank = int(rng.integers(1, 4))
    shape = tuple(int(rng.integers(2, 12)) for _ in range(rank))
    dtype = rng.choice([np.float32, np.int32, np.uint8])
    frames = []
    for i in range(n):
        x = np.zeros(shape, dtype)
        # 1-in-5 frames hit an exact extreme so the empty-sentinel and
        # fully-dense encoder paths really run (a uniform draw almost
        # never produces either)
        r = int(rng.integers(0, 5))
        density = 0.0 if r == 0 else 1.0 if r == 1 else float(rng.uniform(0, 1))
        k = int(round(x.size * density))
        if k:
            pos = rng.choice(x.size, size=k, replace=False)
            vals = rng.integers(1, 100, k)
            x.reshape(-1)[pos] = vals.astype(dtype)
        frames.append(Frame.of(x, pts=i))
    got = []
    p = Pipeline()
    chain = [p.add(DataSrc(data=[f.with_tensors((f.tensor(0).copy(),))
                                 for f in frames]))]
    chain.append(p.add(make("tensor_sparse_enc")))
    if rng.integers(0, 2):
        chain.append(p.add(Queue(max_size_buffers=4)))
    chain.append(p.add(make("tensor_sparse_dec")))
    sink = p.add(TensorSink())
    sink.connect("new-data", got.append)
    chain.append(sink)
    p.link_chain(*chain)
    p.run(timeout=120)
    assert len(got) == n
    for f, out in zip(frames, got):
        np.testing.assert_array_equal(np.asarray(out.tensor(0)),
                                      np.asarray(f.tensor(0)))
        assert out.pts == f.pts


def run_continuous_batching(rng):
    """serving.ContinuousBatcher under randomized membership churn:
    random capacity, random stream lengths, staggered joins/leaves/
    starvation, occasional slot reuse — every stream's outputs must
    match the single-sequence decode loop exactly."""
    import jax.numpy as jnp

    from nnstreamer_tpu.models import transformer
    from nnstreamer_tpu.serving import ContinuousBatcher

    kw = dict(t_max=12, d_in=4, n_out=3, d_model=16, n_heads=2, n_layers=1)
    capacity = int(rng.integers(1, 5))
    n_streams = int(rng.integers(1, capacity + 3))  # more streams than slots
    lengths = [int(rng.integers(1, 9)) for _ in range(n_streams)]
    streams = [
        [rng.standard_normal(kw["d_in"]).astype(np.float32)
         for _ in range(n)]
        for n in lengths
    ]
    got = [[] for _ in streams]
    with ContinuousBatcher(capacity=capacity, seed=int(rng.integers(4)),
                           **kw) as eng:
        pending = list(range(n_streams))
        live = {}  # stream idx -> (session, iterator position)
        while pending or live:
            if pending and len(live) < capacity and rng.random() < 0.7:
                k = pending.pop(0)
                live[k] = (eng.open_session(timeout=30), 0)
            if not live:
                continue
            # random live stream advances one step; others starve
            k = list(live)[int(rng.integers(0, len(live)))]
            sess, i = live[k]
            sess.feed(streams[k][i])
            got[k].append(sess.get(timeout=60))
            if i + 1 >= lengths[k]:
                sess.close()
                del live[k]
            else:
                live[k] = (sess, i + 1)
        params = eng.params
    for k, xs in enumerate(streams):
        cache = transformer.init_decode_cache(
            kw["n_layers"], kw["d_model"], kw["t_max"])
        pos = jnp.zeros((1,), np.int32)
        for i, x in enumerate(xs):
            y, cache, pos = transformer.decode_step(
                params, jnp.asarray(x), cache, pos)
            np.testing.assert_allclose(
                got[k][i], np.asarray(y), rtol=1e-4, atol=1e-4)


TEMPLATES = [run_linear, run_tee, run_mux, run_repo, run_trainer,
             run_renegotiation, run_valve_selector, run_interrupt,
             run_query, run_tensor_if, run_crop, run_rate, run_sparse,
             run_continuous_batching]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--minutes", type=float, default=10.0)
    ap.add_argument("--seed", type=int, default=None)
    args = ap.parse_args()

    if args.seed is not None:  # single-iteration repro
        rng = np.random.default_rng(args.seed)
        fn = TEMPLATES[int(rng.integers(0, len(TEMPLATES)))]
        print(f"repro seed={args.seed}: {fn.__name__}")
        fn(rng)
        print("OK")
        return 0

    try:  # stamp the platform: a TPU soak log must be provably TPU
        import jax

        print(f"jax platform: {jax.devices()[0].platform}", flush=True)
    except Exception as exc:  # noqa: BLE001 — the soak itself still counts
        print(f"jax platform: unavailable ({exc!r})", flush=True)

    t_end = time.time() + args.minutes * 60
    i = fails = 0
    base = int(time.time())
    while time.time() < t_end:
        seed = base + i
        rng = np.random.default_rng(seed)
        fn = TEMPLATES[int(rng.integers(0, len(TEMPLATES)))]
        try:
            fn(rng)
            print(f"[{i}] {fn.__name__} seed={seed} OK", flush=True)
        except Exception:
            fails += 1
            print(f"[{i}] {fn.__name__} seed={seed} FAILED", flush=True)
            traceback.print_exc()
        i += 1
    print(f"campaign done: {i} iterations, {fails} failures")
    return 1 if fails else 0


if __name__ == "__main__":
    sys.exit(main())
