#!/usr/bin/env python
"""Diagnose the axon TPU tunnel's state: ALIVE / SICK / WEDGED.

Round-3 field observations (see .claude/skills/verify/SKILL.md):
- the tunnel wedges for hours (backend init never returns);
- short of a wedge, the transfer path oscillates >100× (150 KB put:
  0.3 ms healthy ↔ 30 ms sick) while async dispatch of device-resident
  work stays fast.

This probe runs each stage in a subprocess with a timeout (a wedged PJRT
client can't be interrupted in-process) and prints one JSON verdict:

    {"state": "ALIVE|SICK|WEDGED|NO_ACCEL|PROBE_ERROR", "init_s": ..,
     "put_150k_ms": .., "dispatch_ms": .., "matmul_ms": ..}

Exit code: 0 ALIVE, 1 SICK, 2 WEDGED/NO_ACCEL, 3 PROBE_ERROR (broken
environment — fix the install, don't pin CPU).
"""

import json
import os
import subprocess
import sys
import time

PROBE = r"""
import time, json
t0 = time.perf_counter()
import jax, jax.numpy as jnp
import numpy as np
dev = jax.devices()[0]
init_s = time.perf_counter() - t0
out = {"platform": dev.platform, "init_s": round(init_s, 2)}
x = jnp.ones((256, 256), jnp.bfloat16)
(x @ x).block_until_ready()
t0 = time.perf_counter()
for _ in range(10):
    y = x @ x
y.block_until_ready()
out["matmul_ms"] = round((time.perf_counter() - t0) / 10 * 1e3, 3)
rng = np.random.default_rng(0)
arrs = [rng.integers(0, 256, 150_528).astype(np.uint8) for _ in range(10)]
t0 = time.perf_counter()
ds = [jax.device_put(a) for a in arrs]
jax.block_until_ready(ds)
out["put_150k_ms"] = round((time.perf_counter() - t0) / 10 * 1e3, 3)
t0 = time.perf_counter()
for d in ds:
    z = d + 1
z.block_until_ready()
out["dispatch_ms"] = round((time.perf_counter() - t0) / 10 * 1e3, 3)
print(json.dumps(out))
"""


def main() -> int:
    timeout = float(os.environ.get("DOCTOR_TIMEOUT", "90"))
    t0 = time.time()
    try:
        proc = subprocess.run(
            [sys.executable, "-c", PROBE],
            capture_output=True, text=True, timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        print(json.dumps({
            "state": "WEDGED",
            "detail": f"probe did not return within {timeout:g}s "
                      "(symptom: stuck in make_c_api_client; wedges can "
                      "last hours — pin CPU and keep working)",
        }))
        return 2
    if proc.returncode != 0:
        # a fast non-zero exit is a broken environment (missing jax, bad
        # config), not a wedged tunnel — don't tell the operator to "pin
        # CPU and keep working" when the fix is the install
        wall = time.time() - t0
        wedged = wall > timeout * 0.5
        print(json.dumps({
            "state": "WEDGED" if wedged else "PROBE_ERROR",
            "probe_s": round(wall, 1),
            "detail": proc.stderr.strip()[-300:],
        }))
        return 2 if wedged else 3
    info = json.loads(proc.stdout.strip().splitlines()[-1])
    if info.get("platform") == "cpu":
        info["state"] = "NO_ACCEL"
        print(json.dumps(info))
        return 2
    sick = info["put_150k_ms"] > 5.0 or info["matmul_ms"] > 20.0
    info["state"] = "SICK" if sick else "ALIVE"
    info["wall_s"] = round(time.time() - t0, 1)
    print(json.dumps(info))
    return 1 if sick else 0


if __name__ == "__main__":
    sys.exit(main())
